"""Attention: Pallas flash-attention kernel for TPU + XLA reference path.

Layout convention everywhere: [batch, seq, heads, head_dim] at module
boundaries ("BSHD"); the flash kernel internally works per (batch, head)
grid cell. GQA is supported natively — K/V carry n_kv_heads and the kernel's
BlockSpec index_map points each query head at its KV group, so grouped KV is
never materialized at full head count (saves HBM bandwidth, the usual TPU
bottleneck).

The flash kernel is the canonical online-softmax blockwise algorithm: grid
(batch, q_heads, q_blocks, k_blocks) with the k dimension innermost;
running max / normalizer / output accumulator live in VMEM scratch that
persists across the sequential k iterations, finalized on the last k block.
Causal masking skips fully-masked k blocks via pl.when.

No counterpart in the reference repo (a Go web framework, SURVEY.md §2.9);
this implements the TPU north star's compute path (BASELINE.json).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU-specific memory spaces; absent in some CPU-only builds
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

NEG_INF = -2.3819763e38  # close to bf16 min; avoids nan from (-inf) - (-inf)


# ---------------------------------------------------------------------------
# Reference path (XLA). Used on CPU, for odd shapes, and as the test oracle.
# ---------------------------------------------------------------------------


def mha_reference(
    q: jnp.ndarray,  # [b, sq, hq, d]
    k: jnp.ndarray,  # [b, sk, hkv, d]
    v: jnp.ndarray,  # [b, sk, hkv, d]
    *,
    causal: bool = True,
    scale: float | None = None,
    logit_cap: float = 0.0,
    kv_mask: jnp.ndarray | None = None,  # [b, sk] bool, True = attend
    q_positions: jnp.ndarray | None = None,  # [b, sq] absolute positions
    window: int = 0,  # sliding window: attend to (q_pos - window, q_pos]
) -> jnp.ndarray:
    b, sq, hq, d = q.shape
    hkv = k.shape[2]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    group = hq // hkv

    qf = q.astype(jnp.float32) * scale
    # [b, hkv, group, sq, d] x [b, hkv, sk, d] -> [b, hkv, group, sq, sk]
    qg = qf.transpose(0, 2, 1, 3).reshape(b, hkv, group, sq, d)
    kf = k.astype(jnp.float32).transpose(0, 2, 1, 3)  # [b, hkv, sk, d]
    vf = v.astype(jnp.float32).transpose(0, 2, 1, 3)
    logits = jnp.einsum("bkgqd,bksd->bkgqs", qg, kf)
    if logit_cap > 0.0:
        logits = logit_cap * jnp.tanh(logits / logit_cap)

    sk = k.shape[1]
    mask = jnp.ones((b, sq, sk), dtype=bool)
    if causal or window > 0:
        qpos = (
            q_positions
            if q_positions is not None
            else jnp.broadcast_to(jnp.arange(sq), (b, sq))
        )
        kpos = jnp.arange(sk)
        if causal:
            mask = mask & (kpos[None, None, :] <= qpos[:, :, None])
        if window > 0:
            # sliding window (Mistral): keys older than window-1 positions
            # before the query are masked out
            mask = mask & (kpos[None, None, :] > qpos[:, :, None] - window)
    if kv_mask is not None:
        mask = mask & kv_mask[:, None, :]
    logits = jnp.where(mask[:, None, None, :, :], logits, NEG_INF)

    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bkgqs,bksd->bkgqd", probs, vf)
    out = out.reshape(b, hq, sq, d).transpose(0, 2, 1, 3)
    return out.astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas flash attention (TPU prefill path)
# ---------------------------------------------------------------------------


def _flash_kernel(
    *refs,  # [off_ref?, q_ref, k_ref, v_ref, o_ref, m, l, acc]
    causal: bool,
    scale: float,
    logit_cap: float,
    window: int,
    block_q: int,
    block_k: int,
    num_k_blocks: int,
    offset: bool = False,
):
    # Ref layout: inputs (optionally led by the per-batch query-offset
    # scalar in SMEM — the chunk-append prefill path), then the output,
    # then VMEM scratch: running max / denom (lane-replicated) + f32
    # accumulator, persistent across the sequential k iterations.
    if offset:
        off_ref, q_ref, k_ref, v_ref, o_ref, m_scratch, l_scratch, acc_scratch = refs
    else:
        off_ref = None
        q_ref, k_ref, v_ref, o_ref, m_scratch, l_scratch, acc_scratch = refs
    qi = pl.program_id(2)
    ki_raw = pl.program_id(3)
    grid_k = pl.num_programs(3)

    @pl.when(ki_raw == 0)
    def _init():
        m_scratch[:] = jnp.full_like(m_scratch, NEG_INF)
        l_scratch[:] = jnp.zeros_like(l_scratch)
        acc_scratch[:] = jnp.zeros_like(acc_scratch)

    # Banded grid (causal sliding window): the k grid dim spans only the
    # band-intersecting blocks; remap ki_raw to the ACTUAL k block index
    # ending at this q block's last needed block — the same formula the
    # BlockSpec index_map uses, so compute positions match the DMA'd
    # block. An unclamped index < 0 means this slot aliases block 0's DMA
    # (early q blocks) and must be skipped or block 0 double-counts.
    banded = causal and window > 0 and grid_k < num_k_blocks
    if banded:
        kb_hi = ((qi + 1) * block_q - 1) // block_k
        ki_unclamped = kb_hi - (grid_k - 1) + ki_raw
        ki = jnp.maximum(ki_unclamped, 0)
        in_range = ki_unclamped >= 0
    else:
        ki = ki_raw
        in_range = True

    # Per-batch query offset (chunk-append prefill): query row i sits at
    # absolute position off + i while key block positions stay absolute
    # cache row indices. The offset is a traced value, so block liveness
    # is decided compute-side (pl.when takes dynamic predicates); the
    # banded-grid DMA skip stays disabled on this path (flash_attention
    # never requests both).
    off = off_ref[0, 0] if offset else 0

    # Causal: block is live iff some query position >= some key position,
    # i.e. block_q_end >= block_k_start. Sliding window additionally kills
    # blocks entirely BEHIND the band (block_k_end <= block_q_start -
    # window) — with the banded grid those blocks aren't even fetched;
    # without it (non-causal or tiny seq) they are skipped compute-side.
    live = off + (qi + 1) * block_q - 1 >= ki * block_k if causal else True
    if window > 0:
        band_live = (ki + 1) * block_k - 1 > off + qi * block_q - window
        live = jnp.logical_and(live, band_live) if causal else band_live
    live = jnp.logical_and(live, in_range) if banded else live

    @pl.when(live)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32) * scale  # [block_q, d]
        k = k_ref[0, 0].astype(jnp.float32)  # [block_k, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [block_q, block_k]
        if logit_cap > 0.0:
            s = logit_cap * jnp.tanh(s / logit_cap)
        if causal or window > 0:
            qpos = off + qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            kpos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            if causal:
                s = jnp.where(kpos <= qpos, s, NEG_INF)
            if window > 0:
                s = jnp.where(kpos > qpos - window, s, NEG_INF)

        m_prev = m_scratch[:, :1]  # [block_q, 1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)  # [block_q, block_k]
        alpha = jnp.exp(m_prev - m_new)  # [block_q, 1]
        l_new = alpha * l_scratch[:, :1] + jnp.sum(p, axis=-1, keepdims=True)

        m_scratch[:] = jnp.broadcast_to(m_new, m_scratch.shape)
        l_scratch[:] = jnp.broadcast_to(l_new, l_scratch.shape)
        acc_scratch[:] = acc_scratch[:] * alpha + jax.lax.dot_general(
            p,
            v_ref[0, 0].astype(jnp.float32),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(ki_raw == grid_k - 1)
    def _finalize():
        denom = l_scratch[:, :1]
        denom = jnp.where(denom == 0.0, 1.0, denom)  # fully-masked rows -> 0
        o_ref[0, 0] = (acc_scratch[:] / denom).astype(o_ref.dtype)


def flash_attention(
    q: jnp.ndarray,  # [b, sq, hq, d]
    k: jnp.ndarray,  # [b, sk, hkv, d]
    v: jnp.ndarray,  # [b, sk, hkv, d]
    *,
    causal: bool = True,
    scale: float | None = None,
    logit_cap: float = 0.0,
    window: int = 0,
    block_q: int = 128,
    block_k: int = 128,
    q_offsets: jnp.ndarray | None = None,  # [b] int32 per-batch query offset
    interpret: bool = False,
) -> jnp.ndarray:
    """Blockwise online-softmax attention on the Pallas TPU kernel.

    q_offsets (chunk-append prefill): query row i of batch b sits at
    absolute position q_offsets[b] + i while key positions stay absolute
    cache row indices — a query block attends all prior keys already
    resident in the cache plus its own chunk's causal triangle. Offsets
    are traced values, so block liveness is decided in-kernel and the
    banded-grid DMA skip is disabled on this path (every k block is
    fetched; masked blocks are skipped compute-side)."""
    if not _HAS_PLTPU:
        raise RuntimeError(
            "flash_attention requires jax.experimental.pallas.tpu (scratch "
            "memory spaces); use mha_reference / multi_head_attention instead"
        )
    b, sq, hq, d = q.shape
    sk, hkv = k.shape[1], k.shape[2]
    if sq % block_q or sk % block_k:
        raise ValueError(f"seq lengths ({sq},{sk}) must divide blocks ({block_q},{block_k})")
    if hq % hkv:
        raise ValueError(f"q heads {hq} not a multiple of kv heads {hkv}")
    group = hq // hkv
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    num_k_blocks = sk // block_k
    offset = q_offsets is not None

    # BHSD layout inside the kernel: contiguous [seq, d] slabs per head.
    qt = q.transpose(0, 2, 1, 3)  # [b, hq, sq, d]
    kt = k.transpose(0, 2, 1, 3)  # [b, hkv, sk, d]
    vt = v.transpose(0, 2, 1, 3)

    # Banded grid for causal sliding windows: only the k blocks that can
    # intersect a q block's band are iterated (and hence DMA'd) — the
    # measured difference at 16k/window-1024 is the dead-block K/V copies,
    # not the skipped compute. The exact per-q-block count is periodic in
    # the q-block start mod block_k (plus a ramp while the band clips at
    # 0), so take the true max over one ramp + one period of q blocks —
    # a closed-form bound over-fetches one dead block per q block at the
    # shipped aligned 128/128 config. Dynamic q_offsets make the band
    # data-dependent, so the offset path keeps the full k grid.
    if causal and window > 0 and not offset:
        nqb = sq // block_q
        limit = min(
            nqb, (window - 1) // block_q + math.lcm(block_q, block_k) // block_q + 1
        )
        grid_k = max(
            (qi * block_q + block_q - 1) // block_k
            - max(0, qi * block_q - window + 1) // block_k
            + 1
            for qi in range(limit)
        )
        grid_k = min(grid_k, num_k_blocks)
    else:
        grid_k = num_k_blocks

    def kv_index(bi, hi, qi, ki):
        if grid_k == num_k_blocks:
            return (bi, hi // group, ki, 0)
        kb_hi = ((qi + 1) * block_q - 1) // block_k
        return (bi, hi // group, jnp.maximum(kb_hi - (grid_k - 1) + ki, 0), 0)

    grid = (b, hq, sq // block_q, grid_k)
    kernel = functools.partial(
        _flash_kernel,
        causal=causal,
        scale=scale,
        logit_cap=logit_cap,
        window=window,
        block_q=block_q,
        block_k=block_k,
        num_k_blocks=num_k_blocks,
        offset=offset,
    )
    in_specs = [
        pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
        pl.BlockSpec((1, 1, block_k, d), kv_index),
        pl.BlockSpec((1, 1, block_k, d), kv_index),
    ]
    operands = [qt, kt, vt]
    if offset:
        # per-batch scalar in SMEM, one (1, 1) cell per grid batch index
        in_specs.insert(0, pl.BlockSpec(
            (1, 1), lambda bi, hi, qi, ki: (bi, 0),
            memory_space=pltpu.SMEM,
        ))
        operands.insert(0, q_offsets.astype(jnp.int32).reshape(b, 1))
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, 1, block_q, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0)
        ),
        out_shape=jax.ShapeDtypeStruct((b, hq, sq, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        interpret=interpret,
    )(*operands)
    return out.transpose(0, 2, 1, 3)


# ---------------------------------------------------------------------------
# Decode attention (single query step against a KV cache)
# ---------------------------------------------------------------------------


def ring_positions(lengths: jnp.ndarray, capacity: int) -> jnp.ndarray:
    """Absolute position held by each row of a window-bounded ROLLING
    (ring) KV cache. A ring cache of `capacity` C stores position p at row
    p mod C, overwriting as the sequence grows, so a slot costs O(window)
    memory instead of O(max_len) (gofr_tpu.kvcache). Row j therefore holds
    the LAST position congruent to j written so far:

        p(j) = t-1 - ((t-1-j) mod C)    for t = lengths tokens written

    p(j) < 0 marks a never-written row (including the whole cache at
    t == 0, where t-1 = -1 makes every p negative). Returns [b, capacity]
    int32."""
    j = jnp.arange(capacity, dtype=jnp.int32)
    t1 = lengths[:, None].astype(jnp.int32) - 1  # [b, 1]
    return t1 - jnp.mod(t1 - j[None, :], capacity)


def decode_attention(
    q: jnp.ndarray,  # [b, 1, hq, d]
    k_cache: jnp.ndarray,  # [b, max_len, hkv, d]
    v_cache: jnp.ndarray,  # [b, max_len, hkv, d]
    lengths: jnp.ndarray,  # [b] int32 — valid prefix length per sequence
    *,
    scale: float | None = None,
    logit_cap: float = 0.0,
    window: int = 0,  # sliding window over absolute positions
    ring: int = 0,  # >0: k/v_cache is a ring of this capacity (kvcache)
) -> jnp.ndarray:
    """Decode is HBM-bandwidth-bound, so the einsums read the cache at its
    STORED dtype (f32 accumulation via preferred_element_type) — routing
    through mha_reference cast the whole cache to f32 first, tripling the
    dominant KV stream (measured r3: 1-layer cost 3x). A hand kernel buys
    nothing beyond this at decode's arithmetic intensity; the
    compiler-friendly einsum form lets XLA fuse the mask and softmax.

    ring > 0 declares the cache a window-bounded ROLLING buffer of that
    capacity (row index = absolute position mod ring, ring == max_len):
    masks are computed from each row's reconstructed absolute position
    instead of its index. Requires window > 0 and ring >= window so every
    in-window position is still resident."""
    b, sq, hq, d = q.shape
    hkv = k_cache.shape[2]
    group = hq // hkv
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    max_len = k_cache.shape[1]

    # q * scale stays lossless in bf16 for power-of-two head dims (the only
    # shapes we ship); the f32 path is bitwise-identical either way.
    qg = (q.astype(jnp.float32) * scale).astype(q.dtype)
    qg = qg.reshape(b, sq, hkv, group, d)
    s = jnp.einsum(
        "bqhgd,bkhd->bhgqk", qg, k_cache, preferred_element_type=jnp.float32
    )  # [b, hkv, group, sq, max_len]
    if logit_cap > 0.0:
        s = logit_cap * jnp.tanh(s / logit_cap)
    if ring > 0:
        if window <= 0 or ring < window:
            raise ValueError(
                f"ring cache (capacity {ring}) requires 0 < window <= ring, "
                f"got window {window}"
            )
        # ring row j holds absolute position p(j); valid iff ever written
        # (p >= 0) and inside the window ending at the query (abs position
        # lengths-1): p >= lengths - window
        pos = ring_positions(lengths, max_len)  # [b, max_len]
        kv_mask = (pos >= 0) & (pos >= lengths[:, None] - window)
    else:
        kv_mask = jnp.arange(max_len)[None, :] < lengths[:, None]  # [b, max_len]
        if window > 0:
            # query sits at absolute position lengths-1: keep [lengths-window, ..)
            kv_mask = kv_mask & (
                jnp.arange(max_len)[None, :] >= lengths[:, None] - window
            )
    s = jnp.where(kv_mask[:, None, None, None, :], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1).astype(v_cache.dtype)
    out = jnp.einsum(
        "bhgqk,bkhd->bqhgd", p, v_cache, preferred_element_type=jnp.float32
    )
    return out.reshape(b, sq, hq, d).astype(q.dtype)


def chunk_decode_attention(
    q: jnp.ndarray,  # [b, 1, hq, d]
    k_cache: jnp.ndarray,  # [b, max_len, hkv, d] — read-only inside a chunk
    v_cache: jnp.ndarray,  # [b, max_len, hkv, d]
    k_buf: jnp.ndarray,  # [b, chunk, hkv, d] — this chunk's new K rows
    v_buf: jnp.ndarray,  # [b, chunk, hkv, d]
    lengths: jnp.ndarray,  # [b] valid main-cache prefix (at chunk START)
    step: jnp.ndarray,  # scalar int32 — current step within the chunk
    *,
    scale: float | None = None,
    logit_cap: float = 0.0,
    window: int = 0,  # sliding window over absolute positions
    ring: int = 0,  # >0: main cache is a rolling ring of this capacity
) -> jnp.ndarray:
    """Decode attention over main cache + chunk ring buffer.

    The serving engine's fused decode chunk never writes the big KV cache at
    per-sequence cursors (a vmap'd scatter XLA lowers terribly — measured
    ~3.5 ms/step across 18 layers, 6x the attention itself). Instead each
    step writes its K/V at the UNIFORM position `step` of a small per-chunk
    buffer (one cheap dynamic_update_slice), the main cache stays read-only,
    and the buffer is merged into per-slot cursor positions ONCE per chunk.
    This function attends over both regions with one joint softmax:
    main positions masked to < lengths, buffer positions masked to <= step.

    ring > 0 declares the MAIN cache a window-bounded rolling buffer of
    that capacity (row index = absolute position mod ring — see
    ring_positions / gofr_tpu.kvcache): main-cache masks derive from each
    row's reconstructed absolute position. The chunk buffer is position-
    indexed either way, so its masks are unchanged.
    """
    b, sq, hq, d = q.shape
    hkv = k_cache.shape[2]
    group = hq // hkv
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    max_len, chunk = k_cache.shape[1], k_buf.shape[1]

    qg = (q.astype(jnp.float32) * scale).astype(q.dtype)
    qg = qg.reshape(b, sq, hkv, group, d)
    s_main = jnp.einsum(
        "bqhgd,bkhd->bhgqk", qg, k_cache, preferred_element_type=jnp.float32
    )
    s_buf = jnp.einsum(
        "bqhgd,bkhd->bhgqk", qg, k_buf, preferred_element_type=jnp.float32
    )
    if logit_cap > 0.0:
        s_main = logit_cap * jnp.tanh(s_main / logit_cap)
        s_buf = logit_cap * jnp.tanh(s_buf / logit_cap)
    if ring > 0:
        if window <= 0 or ring < window:
            raise ValueError(
                f"ring cache (capacity {ring}) requires 0 < window <= ring, "
                f"got window {window}"
            )
        # query's absolute position is lengths + step; ring row j holds
        # absolute position pos(j) <= lengths-1 (causality is implied),
        # valid iff ever written and inside the query's window
        pos = ring_positions(lengths, max_len)  # [b, max_len]
        main_mask = (pos >= 0) & (pos > lengths[:, None] + step - window)
    else:
        main_mask = jnp.arange(max_len)[None, :] < lengths[:, None]  # [b, max_len]
        if window > 0:
            # query's absolute position is lengths + step; main-cache rows
            # live at absolute 0..lengths-1 and buffer row i at lengths + i
            main_mask = main_mask & (
                jnp.arange(max_len)[None, :] > lengths[:, None] + step - window
            )
    buf_mask = jnp.arange(chunk)[None, :] <= step  # [1, chunk]
    if window > 0:
        buf_mask = buf_mask & (jnp.arange(chunk)[None, :] > step - window)
    s_main = jnp.where(main_mask[:, None, None, None, :], s_main, NEG_INF)
    s_buf = jnp.where(buf_mask[:, None, None, None, :], s_buf, NEG_INF)

    # one softmax across both regions without concatenating the caches
    m = jnp.maximum(
        jnp.max(s_main, axis=-1, keepdims=True), jnp.max(s_buf, axis=-1, keepdims=True)
    )
    p_main = jnp.exp(s_main - m)
    p_buf = jnp.exp(s_buf - m)
    denom = jnp.sum(p_main, axis=-1, keepdims=True) + jnp.sum(
        p_buf, axis=-1, keepdims=True
    )
    p_main = (p_main / denom).astype(v_cache.dtype)
    p_buf = (p_buf / denom).astype(v_buf.dtype)
    out = jnp.einsum(
        "bhgqk,bkhd->bqhgd", p_main, v_cache, preferred_element_type=jnp.float32
    ) + jnp.einsum(
        "bhgqk,bkhd->bqhgd", p_buf, v_buf, preferred_element_type=jnp.float32
    )
    return out.reshape(b, sq, hq, d).astype(q.dtype)


def chunk_prefill_attention(
    q: jnp.ndarray,  # [b, c, hq, d] — one prefill chunk's queries
    k_cache: jnp.ndarray,  # [b, capacity, hkv, d] — chunk rows ALREADY written
    v_cache: jnp.ndarray,  # [b, capacity, hkv, d]
    cursors: jnp.ndarray,  # [b] int32 — tokens resident BEFORE this chunk
    *,
    scale: float | None = None,
    logit_cap: float = 0.0,
    window: int = 0,  # sliding window over absolute positions
    ring: int = 0,  # >0: cache is a rolling ring of this capacity (kvcache)
) -> jnp.ndarray:
    """Chunked-prefill attention: a query block at absolute positions
    [cursors, cursors + c) attends every prior key resident in the slot
    cache plus this chunk's own causal triangle — the device-side core of
    the token-budget step scheduler (gofr_tpu.llm), which appends prompts
    into slot KV incrementally instead of prefilling them in one
    monolithic wave.

    The chunk's K/V rows are written into the cache BEFORE this call
    (write-then-attend), so one einsum over the capacity axis covers both
    regions and the softmax needs no two-region merge. Masks are purely
    positional: row p is attended by query i iff p <= cursors + i (causal
    — this also hides any stale rows a previous slot occupant left above
    the cursor) and p > cursors + i - window when windowed. Queries
    beyond the chunk's valid token count produce garbage the engine
    discards; their key rows were never written (the engine drops those
    scatter indices), and causality hides whatever sits there.

    ring > 0 declares the cache a window-bounded rolling buffer of that
    capacity: row positions are reconstructed via ring_positions at the
    post-chunk length (cursors + c), never-written rows come back
    negative, and the same positional masks apply. Requires
    0 < window <= ring - c so a chunk append can never overwrite a row
    still inside any query's window.

    Dots run at the cache's stored dtype with f32 accumulation (the
    decode_attention convention); on the TPU backend with cleanly tiling
    shapes the dense path lowers to the Pallas flash kernel via
    q_offsets (chunks narrower than one 8-row sublane tile — the
    speculative-decoding verify widths, draft + 1 queries — stay on the
    XLA path: a sub-tile block_q has no MXU-aligned lowering).

    SPECULATIVE-DECODING ROLLBACK CONTRACT (gofr_tpu.spec): the verify
    path appends draft rows with this same write-then-attend call and,
    on rejection, rolls the slot cursor back BELOW rows already written.
    Those stale rows are invisible by construction, on both layouts:

    - dense: stale rows sit at positions > every later query's cursor
      until overwritten, and the causal mask (p <= cursors + i) hides
      them — the same property that hides a previous slot occupant's
      rows above the cursor;
    - ring: ring_positions reconstructs row j's position as the LAST
      position congruent to j below the current length, so a stale row
      reads as one full lap (capacity) behind its true position; with
      capacity >= window + chunk that reconstructed position is always
      outside every query's window, and the row is masked until the
      cursor re-reaches it and overwrites it (write-then-attend order).
    """
    b, c, hq, d = q.shape
    hkv = k_cache.shape[2]
    group = hq // hkv
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    capacity = k_cache.shape[1]

    qpos = cursors[:, None] + jnp.arange(c, dtype=jnp.int32)[None, :]  # [b, c]
    if ring > 0:
        if window <= 0 or ring - c < window:
            # window <= ring - c (the docstring precondition): appending a
            # c-row chunk must never overwrite a row still inside any
            # query's window — violations vanish into the mask silently
            raise ValueError(
                f"ring cache (capacity {ring}) requires 0 < window <= "
                f"ring - chunk ({ring - c}), got window {window}"
            )
        pos = ring_positions(cursors + c, capacity)  # [b, capacity]
        mask = (pos[:, None, :] >= 0) & (pos[:, None, :] <= qpos[:, :, None])
        mask = mask & (pos[:, None, :] > qpos[:, :, None] - window)
    else:
        if (
            _flash_ok(q, k_cache, min(128, c), 128)
            and c % min(128, c) == 0
            and c % 8 == 0  # sub-sublane widths (spec verify) stay on XLA
        ):
            # dense path on TPU: the flash kernel accepts the query block
            # via per-batch offsets (block_q clamped to the chunk length)
            return flash_attention(
                q, k_cache, v_cache, causal=True, scale=scale,
                logit_cap=logit_cap, window=window,
                block_q=min(128, c), q_offsets=cursors,
            )
        kpos = jnp.arange(capacity, dtype=jnp.int32)[None, None, :]
        mask = kpos <= qpos[:, :, None]
        if window > 0:
            mask = mask & (kpos > qpos[:, :, None] - window)

    qg = (q.astype(jnp.float32) * scale).astype(q.dtype)
    qg = qg.reshape(b, c, hkv, group, d)
    s = jnp.einsum(
        "bqhgd,bkhd->bhgqk", qg, k_cache, preferred_element_type=jnp.float32
    )  # [b, hkv, group, c, capacity]
    if logit_cap > 0.0:
        s = logit_cap * jnp.tanh(s / logit_cap)
    s = jnp.where(mask[:, None, None, :, :], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1).astype(v_cache.dtype)
    out = jnp.einsum(
        "bhgqk,bkhd->bqhgd", p, v_cache, preferred_element_type=jnp.float32
    )
    return out.reshape(b, c, hq, d).astype(q.dtype)


# ---------------------------------------------------------------------------
# Paged attention (block-pool KV; gofr_tpu.kvcache.paged)
# ---------------------------------------------------------------------------
#
# Decode against a BLOCK-PAGED KV pool: per-sequence block tables map
# logical row p to pool row table[p // B] * B + p % B, so the decode read
# stream follows the table instead of a contiguous slab. Two paths,
# selected at trace time exactly like the flash kernel:
#
# - Pallas TPU kernel (_paged_decode_partials): grid (batch, kv_head,
#   table_slot); the block table and the per-sequence valid bounds ride
#   as SCALAR PREFETCH operands, so each grid cell's BlockSpec index_map
#   DMAs pool block table[b, j] directly — the pool is never gathered
#   into a dense copy, which is the whole point (decode is HBM-bound;
#   a gather would double the dominant stream). Returns online-softmax
#   PARTIALS (normalized output + running max + denom) so the caller can
#   merge the chunk ring buffer region with one rescale.
# - Dense-gather reference (paged_gather): jnp.take the table rows into
#   the contiguous layout and reuse the proven attention above — the
#   CPU/old-jax fallback and the test oracle. Bit-exact with the
#   contiguous engine because gathering blocks in table order
#   reconstructs the same slab.
#
# int8 KV blocks (TPU_LLM_KV_INT8): the pool stores int8 rows plus one
# f32 scale per (row, kv_head); both paths dequantize after the read, so
# the HBM stream the decode loop is bound by moves at half width.


def paged_gather(k_pool, v_pool, tables, *, k_scales=None, v_scales=None, dtype=None):
    """[NB, B, hkv, d] pools -> dense [b, MB*B, hkv, d] views through
    [b, MB] block tables (the reference read path). Stale table entries
    gather stale blocks — callers mask by position exactly as on the
    contiguous layout."""

    def take(pool, sc):
        g = jnp.take(pool, tables, axis=0, mode="clip")  # [b, MB, B, hkv, d]
        b, MB, B, hkv, d = g.shape
        g = g.reshape(b, MB * B, hkv, d)
        if sc is not None:
            s = jnp.take(sc, tables, axis=0, mode="clip").reshape(b, MB * B, hkv)
            g = g.astype(dtype) * s[..., None].astype(dtype)
        return g

    return take(k_pool, k_scales), take(v_pool, v_scales)


def _paged_decode_kernel(
    # scalar prefetch: block tables + per-sequence valid bounds
    tbl_ref, lo_ref, hi_ref,
    # inputs (q, k block, v block[, k scales, v scales]), outputs, scratch
    *refs,
    block: int,
    scale: float,
    logit_cap: float,
    quantized: bool,
):
    if quantized:
        q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref, m_ref, l_ref, m_s, l_s, acc_s = refs
    else:
        q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, m_s, l_s, acc_s = refs
        ks_ref = vs_ref = None
    bi = pl.program_id(0)
    ji = pl.program_id(2)
    nj = pl.num_programs(2)

    @pl.when(ji == 0)
    def _init():
        m_s[:] = jnp.full_like(m_s, NEG_INF)
        l_s[:] = jnp.zeros_like(l_s)
        acc_s[:] = jnp.zeros_like(acc_s)

    lo = lo_ref[bi]
    hi = hi_ref[bi]
    base = ji * block  # logical position of this table slot's first row
    live = jnp.logical_and(base < hi, base + block > lo)

    @pl.when(live)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32) * scale  # [group, d]
        k = k_ref[0, :, 0].astype(jnp.float32)  # [block, d]
        v = v_ref[0, :, 0].astype(jnp.float32)
        if ks_ref is not None:
            k = k * ks_ref[0, :, 0][:, None]
            v = v * vs_ref[0, :, 0][:, None]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [group, block]
        if logit_cap > 0.0:
            s = logit_cap * jnp.tanh(s / logit_cap)
        pos = base + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(jnp.logical_and(pos >= lo, pos < hi), s, NEG_INF)
        m_prev = m_s[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_s[:] = jnp.broadcast_to(
            alpha * l_s[:, :1] + jnp.sum(p, axis=-1, keepdims=True), l_s.shape
        )
        m_s[:] = jnp.broadcast_to(m_new, m_s.shape)
        acc_s[:] = acc_s[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(ji == nj - 1)
    def _finalize():
        denom = l_s[:, :1]
        denom = jnp.where(denom == 0.0, 1.0, denom)
        o_ref[0, 0] = (acc_s[:] / denom).astype(o_ref.dtype)
        m_ref[0, 0] = m_s[:].astype(m_ref.dtype)
        l_ref[0, 0] = l_s[:].astype(l_ref.dtype)


def _paged_decode_partials(
    q: jnp.ndarray,  # [b, hq, d] one query per sequence
    k_pool: jnp.ndarray,  # [NB, B, hkv, d]
    v_pool: jnp.ndarray,
    tables: jnp.ndarray,  # [b, MB] int32 pool block per logical slot
    lo: jnp.ndarray,  # [b] int32 first valid logical position (window)
    hi: jnp.ndarray,  # [b] int32 one past the last valid position
    *,
    scale: float,
    logit_cap: float = 0.0,
    k_scales=None,  # [NB, B, hkv] f32 (int8 pool)
    v_scales=None,
    interpret: bool = False,
):
    """Pallas paged-attention decode over the valid band [lo, hi):
    returns (o [b, hq, d] f32 normalized, m [b, hq] f32, l [b, hq] f32)
    online-softmax partials for region merging."""
    if not _HAS_PLTPU:
        raise RuntimeError("paged decode kernel requires pallas TPU support")
    b, hq, d = q.shape
    NB, B, hkv, _ = k_pool.shape
    MB = tables.shape[1]
    group = hq // hkv
    quantized = k_scales is not None

    qt = q.reshape(b, hkv, group, d)

    def q_index(bi, hi_, ji, tbl, lo_, hi__):
        return (bi, hi_, 0, 0)

    def kv_index(bi, hi_, ji, tbl, lo_, hi__):
        return (tbl[bi, ji], 0, hi_, 0)

    in_specs = [
        pl.BlockSpec((1, 1, group, d), q_index),
        pl.BlockSpec((1, B, 1, d), kv_index),
        pl.BlockSpec((1, B, 1, d), kv_index),
    ]
    operands = [qt, k_pool, v_pool]
    if quantized:

        def sc_index(bi, hi_, ji, tbl, lo_, hi__):
            return (tbl[bi, ji], 0, hi_)

        in_specs += [
            pl.BlockSpec((1, B, 1), sc_index),
            pl.BlockSpec((1, B, 1), sc_index),
        ]
        operands += [k_scales, v_scales]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(b, hkv, MB),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, group, d), q_index),
            pl.BlockSpec((1, 1, group, 128), q_index),
            pl.BlockSpec((1, 1, group, 128), q_index),
        ],
        scratch_shapes=[
            pltpu.VMEM((group, 128), jnp.float32),
            pltpu.VMEM((group, 128), jnp.float32),
            pltpu.VMEM((group, d), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _paged_decode_kernel,
        block=B, scale=scale, logit_cap=logit_cap, quantized=quantized,
    )
    o, m, l = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((b, hkv, group, d), jnp.float32),
            jax.ShapeDtypeStruct((b, hkv, group, 128), jnp.float32),
            jax.ShapeDtypeStruct((b, hkv, group, 128), jnp.float32),
        ],
        interpret=interpret,
    )(
        tables.astype(jnp.int32), lo.astype(jnp.int32), hi.astype(jnp.int32),
        *operands,
    )
    return (
        o.reshape(b, hq, d),
        m[..., 0].reshape(b, hq),
        l[..., 0].reshape(b, hq),
    )


def paged_kernel_ok(head_dim: int, block: int, *, interpret: bool = False) -> bool:
    """Whether the Pallas paged-decode kernel can serve this config:
    TPU backend (or interpret mode for tests), lane-aligned head_dim,
    sublane-aligned block size."""
    if not _HAS_PLTPU:
        return False
    if not interpret and jax.default_backend() != "tpu":
        return False
    return head_dim % 128 == 0 and block % 8 == 0


def paged_chunk_decode_attention(
    q: jnp.ndarray,  # [b, 1, hq, d]
    k_pool: jnp.ndarray,  # [NB, B, hkv, d] (one layer's pool)
    v_pool: jnp.ndarray,
    tables: jnp.ndarray,  # [b, MB] int32
    k_buf: jnp.ndarray,  # [b, chunk, hkv, d] — this chunk's new K rows
    v_buf: jnp.ndarray,
    lengths: jnp.ndarray,  # [b] valid pool prefix (at chunk START)
    step: jnp.ndarray,  # scalar int32 — current step within the chunk
    *,
    scale: float | None = None,
    logit_cap: float = 0.0,
    window: int = 0,
    k_scales=None,
    v_scales=None,
    use_kernel: bool | None = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """chunk_decode_attention reading the MAIN region through a block
    table: pool rows hold logical positions [0, lengths) via the table,
    the chunk ring buffer holds positions [lengths, lengths + step]. The
    Pallas path never materializes the gathered cache (partials merged
    with the dense buffer region by one rescale); the reference path
    gathers and defers to chunk_decode_attention — both produce the
    contiguous path's exact masks and dot products, which is what the
    paged==contiguous token-equality tests pin."""
    b, sq, hq, d = q.shape
    B = k_pool.shape[1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    if use_kernel is None:
        use_kernel = paged_kernel_ok(d, B, interpret=interpret)
    if not use_kernel:
        kc, vc = paged_gather(
            k_pool, v_pool, tables,
            k_scales=k_scales, v_scales=v_scales, dtype=q.dtype,
        )
        return chunk_decode_attention(
            q, kc, vc, k_buf, v_buf, lengths, step,
            scale=scale, logit_cap=logit_cap, window=window, ring=0,
        )
    # main region via the paged kernel: valid band [lo, hi)
    hi = lengths
    if window > 0:
        lo = jnp.maximum(lengths + step - window + 1, 0)
    else:
        lo = jnp.zeros_like(lengths)
    o_m, m_m, l_m = _paged_decode_partials(
        q[:, 0], k_pool, v_pool, tables, lo, hi,
        scale=scale, logit_cap=logit_cap,
        k_scales=k_scales, v_scales=v_scales, interpret=interpret,
    )
    # buffer region (dense, [b, chunk]) — same mask set as
    # chunk_decode_attention's buffer half
    hkv = k_buf.shape[2]
    group = hq // hkv
    chunk = k_buf.shape[1]
    qg = (q.astype(jnp.float32) * scale).reshape(b, 1, hkv, group, d)
    s_buf = jnp.einsum(
        "bqhgd,bkhd->bhgqk", qg, k_buf.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )  # [b, hkv, group, 1, chunk]
    if logit_cap > 0.0:
        s_buf = logit_cap * jnp.tanh(s_buf / logit_cap)
    buf_mask = jnp.arange(chunk)[None, :] <= step
    if window > 0:
        buf_mask = buf_mask & (jnp.arange(chunk)[None, :] > step - window)
    s_buf = jnp.where(buf_mask[:, None, None, None, :], s_buf, NEG_INF)
    m_b = jnp.max(s_buf, axis=-1)  # [b, hkv, group, 1]
    p_buf = jnp.exp(s_buf - m_b[..., None])
    l_b = jnp.sum(p_buf, axis=-1)
    o_b = jnp.einsum(
        "bhgqk,bkhd->bhgqd", p_buf, v_buf.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )  # [b, hkv, group, 1, d] — UNNORMALIZED (divided below)
    m_b = m_b.reshape(b, hq)
    l_b = l_b.reshape(b, hq)
    o_b = o_b.reshape(b, hq, d)
    # merge the two regions' online-softmax partials
    m = jnp.maximum(m_m, m_b)
    a_m = jnp.exp(m_m - m) * l_m
    a_b = jnp.exp(m_b - m)
    denom = a_m + a_b * l_b
    denom = jnp.where(denom == 0.0, 1.0, denom)
    out = (o_m * a_m[..., None] + o_b * a_b[..., None]) / denom[..., None]
    return out[:, None].astype(q.dtype)


# ---------------------------------------------------------------------------
# Dispatcher
# ---------------------------------------------------------------------------


def _flash_ok(q: jnp.ndarray, k: jnp.ndarray, block_q: int, block_k: int) -> bool:
    b, sq, hq, d = q.shape
    sk = k.shape[1]
    return (
        _HAS_PLTPU
        and jax.default_backend() == "tpu"
        and sq % block_q == 0
        and sk % block_k == 0
        and d % 128 == 0
    )


def multi_head_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = True,
    scale: float | None = None,
    logit_cap: float = 0.0,
    kv_mask: jnp.ndarray | None = None,
    q_positions: jnp.ndarray | None = None,
    window: int = 0,
    block_q: int = 128,
    block_k: int = 128,
) -> jnp.ndarray:
    """Platform dispatcher: Pallas flash kernel on TPU when shapes tile
    cleanly onto the MXU (including banded/sliding-window prefill, where
    the kernel skips blocks behind the band), XLA reference otherwise.
    kv_mask/q_positions force the reference path (the flash kernel
    assumes dense right-aligned prefill)."""
    if (
        kv_mask is None and q_positions is None
        and _flash_ok(q, k, block_q, block_k)
    ):
        return flash_attention(
            q, k, v, causal=causal, scale=scale, logit_cap=logit_cap,
            window=window, block_q=block_q, block_k=block_k,
        )
    return mha_reference(
        q, k, v, causal=causal, scale=scale, logit_cap=logit_cap,
        kv_mask=kv_mask, q_positions=q_positions, window=window,
    )
