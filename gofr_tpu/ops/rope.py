"""Rotary position embeddings (RoPE).

Functional, shape-static, position-indexed so the same code path serves
prefill (positions = arange) and decode (positions = per-sequence cursor),
which is what keeps the decode step a single compiled executable.
"""

from __future__ import annotations

import jax.numpy as jnp


def rope_frequencies(head_dim: int, theta: float = 10_000.0) -> jnp.ndarray:
    """Inverse frequencies, shape [head_dim // 2], float32."""
    exponent = jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim
    return 1.0 / (theta**exponent)


def apply_rope(
    x: jnp.ndarray,  # [batch, seq, heads, head_dim]
    positions: jnp.ndarray,  # [batch, seq] int32
    theta: float = 10_000.0,
) -> jnp.ndarray:
    """Rotate pairs (x[..., :d/2], x[..., d/2:]) by position*freq.

    Uses the "split halves" convention (as JAX/Gemma implementations do)
    rather than interleaved pairs; consistent across prefill/decode so the
    choice is unobservable from outside.
    """
    head_dim = x.shape[-1]
    freqs = rope_frequencies(head_dim, theta)  # [d/2]
    angles = positions[..., None].astype(jnp.float32) * freqs  # [b, s, d/2]
    angles = angles[:, :, None, :]  # [b, s, 1, d/2] broadcast over heads
    sin, cos = jnp.sin(angles), jnp.cos(angles)
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    rotated = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return rotated.astype(x.dtype)
