"""gRPC server.

Parity: reference pkg/gofr/grpc.go + pkg/gofr/grpc/log.go — server with
chained recovery + logging interceptors (grpc.go:23-27), RPCLog per call
{ID, StartTime, ResponseTime, Method, StatusCode} (grpc/log.go:58-95),
register_service marks the server for startup (gofr.go:57-61).

Beyond parity (SURVEY.md §3.6 notes the reference asymmetry: gRPC handlers
get no Context): this server also offers **framework-native RPC methods** —
add_unary / add_server_stream register handlers with the SAME
`handler(ctx) -> result` signature HTTP/CLI/pub-sub use, carried over
generic JSON-over-gRPC method handlers (no protoc needed; generated-proto
services still register via register_service). Server-streaming handlers
return/yield chunks — the token-streaming path for LLM decode
(BASELINE.json config 3).
"""

from __future__ import annotations

import asyncio
import inspect
import json
import time
import uuid
from concurrent import futures
from typing import Any, Callable, Iterator

import grpc

from .context import Context

__all__ = ["GRPCServer", "GRPCRequest"]


class GRPCRequest:
    """Adapts a generic JSON request + metadata to the Request interface."""

    def __init__(self, payload: bytes, invocation_context, method: str):
        self.payload = payload
        self._grpc_ctx = invocation_context
        self.method = method
        self.context: dict = {}
        self._meta = dict(invocation_context.invocation_metadata() or [])

    def param(self, key: str) -> str:
        # gRPC metadata keys are always lowercase on the wire; mirror the
        # HTTP Request's case-insensitive lookup so shared handlers work.
        return str(self._meta.get(key.lower(), ""))

    def params(self, key: str) -> list[str]:
        v = self.param(key)
        return [v] if v else []

    def path_param(self, key: str) -> str:
        return self.method if key == "method" else ""

    def bind(self, target: Any = None) -> Any:
        data = json.loads(self.payload) if self.payload else {}
        if target is not None and hasattr(target, "__annotations__"):
            for k, v in data.items():
                if k in target.__annotations__:
                    setattr(target, k, v)
            return target
        return data

    def header(self, key: str) -> str:
        return self.param(key)

    def host_name(self) -> str:
        peer = self._grpc_ctx.peer() or ""
        return peer


def _json_bytes(result: Any) -> bytes:
    return json.dumps(result).encode()


# HTTP-status seam -> gRPC status, for exceptions carrying status_code
# (the statusCodeResponder seam the HTTP edge uses). Overload maps to
# RESOURCE_EXHAUSTED and drain/unavailability to UNAVAILABLE — the two
# codes gRPC client retry policies key on — and a `retry-after` trailer
# (seconds, decimal string) mirrors the HTTP Retry-After header
# (docs/advanced-guide/overload.md).
_STATUS_TO_GRPC = {
    429: grpc.StatusCode.RESOURCE_EXHAUSTED,
    503: grpc.StatusCode.UNAVAILABLE,
}


def _abort_mapped(ctx, e: BaseException) -> bool:
    """Abort the RPC with the mapped gRPC status when `e` carries a
    mappable status_code; False when the caller should fall through to
    its INTERNAL recovery path. abort() raises, so on a mapping this
    never returns."""
    code = _STATUS_TO_GRPC.get(getattr(e, "status_code", None))
    if code is None:
        return False
    retry_after = getattr(e, "retry_after", None)
    if isinstance(retry_after, (int, float)) and 0 < retry_after < float("inf"):
        ctx.set_trailing_metadata((("retry-after", f"{retry_after:.3f}"),))
    ctx.abort(code, str(e) or e.__class__.__name__)
    return True  # pragma: no cover — abort raises


class _Interceptor(grpc.ServerInterceptor):
    """Recovery + logging + tracing in one chain link (grpc.go:24-27,
    grpc/log.go:58-95): wraps every behavior with panic recovery (-> INTERNAL),
    a per-RPC span, and an RPCLog line."""

    def __init__(self, container, tracer=None):
        self.container = container
        self.tracer = tracer

    def intercept_service(self, continuation, handler_call_details):
        handler = continuation(handler_call_details)
        if handler is None:
            return None
        method = handler_call_details.method

        def wrap_unary(behavior):
            def wrapped(request, ctx):
                return self._observed(behavior, request, ctx, method, stream=False)

            return wrapped

        def wrap_stream(behavior):
            def wrapped(request, ctx):
                yield from self._observed_stream(behavior, request, ctx, method)

            return wrapped

        if handler.unary_unary is not None:
            return grpc.unary_unary_rpc_method_handler(
                wrap_unary(handler.unary_unary),
                request_deserializer=handler.request_deserializer,
                response_serializer=handler.response_serializer,
            )
        if handler.unary_stream is not None:
            return grpc.unary_stream_rpc_method_handler(
                wrap_stream(handler.unary_stream),
                request_deserializer=handler.request_deserializer,
                response_serializer=handler.response_serializer,
            )
        return handler  # client-streaming passthrough (rare; still served)

    # -- shared observation plumbing --------------------------------------
    def _span(self, method: str, grpc_ctx=None):
        if self.tracer is None:
            return None
        # W3C trace context rides gRPC metadata (lowercased on the wire);
        # linking it here means engine/handler child spans join the
        # caller's trace instead of starting a fresh one per RPC.
        traceparent = None
        if grpc_ctx is not None:
            meta = dict(grpc_ctx.invocation_metadata() or [])
            traceparent = meta.get("traceparent")
        return self.tracer.start_span(f"grpc{method}", traceparent=traceparent)

    def _log(self, method: str, t0: float, code: str, rpc_id: str) -> None:
        logger = getattr(self.container, "logger", None)
        if logger is not None:
            logger.info(
                {
                    "rpc_id": rpc_id,
                    "method": method,
                    "status_code": code,
                    "response_time_us": round((time.perf_counter() - t0) * 1e6),
                }
            )

    def _observed(self, behavior, request, ctx, method: str, stream: bool):
        t0 = time.perf_counter()
        rpc_id = uuid.uuid4().hex[:16]
        span = self._span(method, ctx)
        try:
            out = behavior(request, ctx)
            self._log(method, t0, "OK", rpc_id)
            return out
        except grpc.RpcError:
            self._log(method, t0, "RPC_ERROR", rpc_id)
            raise
        except Exception as e:  # noqa: BLE001 — recovery interceptor (grpc.go:25)
            code = _STATUS_TO_GRPC.get(getattr(e, "status_code", None))
            if code is not None:
                # overload/drain: a TYPED rejection, not a panic — map it
                # (with the retry-after trailer) instead of masking it
                self._log(method, t0, code.name, rpc_id)
                _abort_mapped(ctx, e)
            logger = getattr(self.container, "logger", None)
            if logger is not None:
                logger.error(f"panic in gRPC handler {method}: {e!r}")
            self._log(method, t0, "INTERNAL", rpc_id)
            ctx.abort(grpc.StatusCode.INTERNAL, "internal error")
        finally:
            if span is not None:
                span.end()

    def _observed_stream(self, behavior, request, ctx, method: str):
        t0 = time.perf_counter()
        rpc_id = uuid.uuid4().hex[:16]
        span = self._span(method, ctx)
        try:
            yield from behavior(request, ctx)
            self._log(method, t0, "OK", rpc_id)
        except grpc.RpcError:
            self._log(method, t0, "RPC_ERROR", rpc_id)
            raise
        except Exception as e:  # noqa: BLE001
            code = _STATUS_TO_GRPC.get(getattr(e, "status_code", None))
            if code is not None:
                self._log(method, t0, code.name, rpc_id)
                _abort_mapped(ctx, e)
            logger = getattr(self.container, "logger", None)
            if logger is not None:
                logger.error(f"panic in gRPC stream handler {method}: {e!r}")
            self._log(method, t0, "INTERNAL", rpc_id)
            ctx.abort(grpc.StatusCode.INTERNAL, "internal error")
        finally:
            if span is not None:
                span.end()


def _run_handler(handler: Callable, ctx: Context) -> Any:
    """Sync or async handlers, same as HTTP (handler.py)."""
    if inspect.iscoroutinefunction(handler):
        return asyncio.run(handler(ctx))
    return handler(ctx)


def _iter_stream_handler(handler: Callable, ctx: Context) -> Iterator[Any]:
    """Stream handlers in every natural shape: sync generator, async
    generator (driven on a private loop so each chunk yields as produced),
    or coroutine returning an iterable."""
    if inspect.isasyncgenfunction(handler):
        agen = handler(ctx)
        loop = asyncio.new_event_loop()
        try:
            while True:
                try:
                    yield loop.run_until_complete(agen.__anext__())
                except StopAsyncIteration:
                    return
        finally:
            loop.run_until_complete(agen.aclose())
            # closing the handler's generator abandons any async
            # generator it was iterating (e.g. GenRequest.astream) —
            # those finalize through the loop's asyncgen hooks, so the
            # hooks must RUN before the loop dies or the inner
            # generator's cleanup (disconnect-cancel: slot freed,
            # finish_reason "disconnect") never executes
            loop.run_until_complete(loop.shutdown_asyncgens())
            loop.close()
    else:
        out = _run_handler(handler, ctx)
        yield from out


class GRPCServer:
    def __init__(self, container, port: int, tracer=None, *, max_workers: int = 16):
        self.container = container
        self.port = port
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers),
            interceptors=[_Interceptor(container, tracer)],
        )
        self._generic_methods: dict[str, dict[str, Any]] = {}
        self._started = False

    # -- generated-proto services (reference register path) ---------------
    def register(self, add_servicer_fn: Callable, servicer: Any) -> None:
        add_servicer_fn(servicer, self._server)

    # -- framework-native JSON methods ------------------------------------
    def add_unary(self, service: str, method: str, handler: Callable) -> None:
        """handler(ctx) -> JSON-serializable. Request payload: JSON bytes."""

        def behavior(request: bytes, grpc_ctx):
            ctx = Context(GRPCRequest(request, grpc_ctx, f"/{service}/{method}"), self.container)
            return _json_bytes(_run_handler(handler, ctx))

        self._generic_methods.setdefault(service, {})[method] = (
            grpc.unary_unary_rpc_method_handler(
                behavior,
                request_deserializer=lambda b: b,
                response_serializer=lambda b: b,
            )
        )

    def add_server_stream(self, service: str, method: str, handler: Callable) -> None:
        """handler(ctx) -> iterator of JSON-serializable chunks (token
        streaming: yield per token).

        Client-disconnect cancellation: the serving loop checks
        ``grpc_ctx.is_active()`` at every chunk and CLOSES the handler
        iterator the moment the peer is gone (cancelled RPC, dead
        connection) — the sync gRPC server abandons the response
        iterator to the GC otherwise, which would let an LLM stream
        decode to completion for a client that hung up. Closing it here,
        on the serving thread, runs the handler's GeneratorExit path
        (GenRequest disconnect-cancel: slot freed, load credited,
        finish_reason "disconnect"; docs/advanced-guide/rollouts.md)."""

        def behavior(request: bytes, grpc_ctx) -> Iterator[bytes]:
            ctx = Context(GRPCRequest(request, grpc_ctx, f"/{service}/{method}"), self.container)
            it = _iter_stream_handler(handler, ctx)
            try:
                for chunk in it:
                    if not grpc_ctx.is_active():
                        break  # peer gone: finally closes the handler
                    yield _json_bytes(chunk)
            finally:
                it.close()

        self._generic_methods.setdefault(service, {})[method] = (
            grpc.unary_stream_rpc_method_handler(
                behavior,
                request_deserializer=lambda b: b,
                response_serializer=lambda b: b,
            )
        )

    # -- lifecycle --------------------------------------------------------
    def start(self) -> None:
        for service, methods in self._generic_methods.items():
            self._server.add_generic_rpc_handlers(
                (grpc.method_handlers_generic_handler(service, methods),)
            )
        bound = self._server.add_insecure_port(f"[::]:{self.port}")
        if self.port == 0:
            self.port = bound
        self._server.start()
        self._started = True

    def shutdown(self, grace: float = 2.0) -> None:
        if self._started:
            self._server.stop(grace)
            self._started = False


# -- JSON-over-gRPC client helpers (for tests and inter-service calls) -----


def json_unary(target: str, service: str, method: str, payload: Any, timeout: float = 10.0) -> Any:
    with grpc.insecure_channel(target) as channel:
        fn = channel.unary_unary(
            f"/{service}/{method}",
            request_serializer=lambda b: b,
            response_deserializer=lambda b: b,
        )
        out = fn(_json_bytes(payload), timeout=timeout)
        return json.loads(out)


def json_server_stream(
    target: str, service: str, method: str, payload: Any, timeout: float = 30.0
) -> Iterator[Any]:
    with grpc.insecure_channel(target) as channel:
        fn = channel.unary_stream(
            f"/{service}/{method}",
            request_serializer=lambda b: b,
            response_deserializer=lambda b: b,
        )
        for chunk in fn(_json_bytes(payload), timeout=timeout):
            yield json.loads(chunk)
