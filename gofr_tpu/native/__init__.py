"""Native (C++) runtime components, compiled on demand.

The reference framework's runtime is compiled Go end to end; this package
holds the TPU framework's native equivalents for the CPU-bound plane —
currently `_gofr_http`, the HTTP/1.1 wire codec behind the protocol-mode
HTTP server (httpcore.cc; used by gofr_tpu/http/nativeserver.py).

Build strategy: pybind11 and pip are unavailable in the image, so the
extension is compiled straight from source with the system g++ against the
running interpreter's headers (`sysconfig`), cached under
``native/_build/`` keyed by source mtime+interpreter. A build failure (no
compiler, exotic platform) degrades gracefully: `load_http_codec()` returns
None and the HTTP plane falls back to the pure-Python parser — behavior is
identical, only slower (see tests/test_native_http.py which asserts
codec/python parity).

Set GOFR_NATIVE=0 to disable native components entirely.
"""

from __future__ import annotations

import importlib.util
import os
import subprocess
import sys
import sysconfig

_HERE = os.path.dirname(os.path.abspath(__file__))
_BUILD_DIR = os.path.join(_HERE, "_build")

_http_codec = None
_http_codec_tried = False
_data_core = None
_data_core_tried = False


def _ext_suffix() -> str:
    return sysconfig.get_config_var("EXT_SUFFIX") or ".so"


def _build(src: str, modname: str) -> str | None:
    """Compile ``src`` into ``_build/<modname><ext_suffix>``; return the
    path, or None if compilation is impossible/fails."""
    src_path = os.path.join(_HERE, src)
    out_path = os.path.join(_BUILD_DIR, modname + _ext_suffix())
    stamp_path = out_path + ".stamp"
    stamp = f"{os.path.getmtime(src_path)}:{sys.version_info[:2]}"
    if os.path.exists(out_path) and os.path.exists(stamp_path):
        try:
            with open(stamp_path) as f:
                if f.read() == stamp:
                    return out_path
        except OSError:
            pass
    include = sysconfig.get_paths()["include"]
    os.makedirs(_BUILD_DIR, exist_ok=True)
    # per-process tmp name: two processes building concurrently must not
    # interleave writes and os.replace a half-written .so into the cache
    tmp_out = out_path + f".tmp.{os.getpid()}"
    cmd = [
        "g++", "-O2", "-std=c++17", "-shared", "-fPIC",
        "-fvisibility=hidden", f"-I{include}", src_path, "-o", tmp_out,
    ]
    try:
        proc = subprocess.run(
            cmd, capture_output=True, text=True, timeout=120, check=False
        )
    except (OSError, subprocess.TimeoutExpired):
        return None
    if proc.returncode != 0:
        # leave a breadcrumb for debugging without crashing the app
        try:
            with open(os.path.join(_BUILD_DIR, modname + ".err"), "w") as f:
                f.write(proc.stderr)
        except OSError:
            pass
        return None
    os.replace(tmp_out, out_path)
    with open(stamp_path, "w") as f:
        f.write(stamp)
    return out_path


def _import_from(path: str, modname: str):
    spec = importlib.util.spec_from_file_location(modname, path)
    if spec is None or spec.loader is None:
        return None
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def load_data_core():
    """Return the `_gofr_data` extension (native batch gather for the
    training data-loader), or None when disabled/unbuildable."""
    global _data_core, _data_core_tried
    if _data_core_tried:
        return _data_core
    _data_core_tried = True
    if os.environ.get("GOFR_NATIVE", "1") == "0":
        return None
    try:
        path = _build("datacore.cc", "_gofr_data")
        if path:
            _data_core = _import_from(path, "_gofr_data")
    except Exception:  # noqa: BLE001 - native load must never break the app
        _data_core = None
    return _data_core


def load_http_codec():
    """Return the `_gofr_http` extension module, building it if needed;
    None when native components are disabled or the build fails."""
    global _http_codec, _http_codec_tried
    if _http_codec_tried:
        return _http_codec
    _http_codec_tried = True
    if os.environ.get("GOFR_NATIVE", "1") == "0":
        return None
    try:
        path = _build("httpcore.cc", "_gofr_http")
        if path:
            _http_codec = _import_from(path, "_gofr_http")
    except Exception:  # noqa: BLE001 - native load must never break the app
        _http_codec = None
    return _http_codec
