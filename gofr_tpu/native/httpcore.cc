// _gofr_http: native HTTP/1.1 wire codec for the gofr_tpu HTTP plane.
//
// Parity note: the reference framework's HTTP plane is compiled Go
// (net/http behind pkg/gofr/httpServer.go:19-50); a pure-Python asyncio
// server cannot sit in the same performance league on the CPU-bound
// config-1 benchmark. This extension moves the per-request wire work
// (request-line + header parse, chunked-body decode, response-head
// serialization) into C++, leaving routing/middleware/handlers in Python.
//
// Exposed functions (CPython C API — pybind11 is not in this image):
//   parse(buffer, offset=0)       -> None | (end, method, target, minor,
//                                            headers dict, content_length,
//                                            flags)
//   parse_chunked(buffer, offset) -> None | (body bytes, end)
//   build_head(status, headers, content_length, close, chunked, body=None)
//                                 -> bytes (head, or head+body when given)
//
// Error protocol: malformed input raises ValueError whose args are
// (http_status, message) so the server can answer 400/413/431/505 without
// string matching. Incomplete input returns None (caller buffers more).
//
// Semantics match the pure-Python parser in gofr_tpu/http/server.py
// (_read_headers/_read_body): header keys lowercased + OWS-stripped,
// duplicate keys last-wins, chunk extensions ignored, trailers skipped.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstring>
#include <cstdint>

namespace {

constexpr Py_ssize_t MAX_BODY = 100LL * 1024 * 1024;  // matches server.py cap

// flags returned by parse()
constexpr int F_CHUNKED = 1;
constexpr int F_CLOSE = 2;
constexpr int F_EXPECT_CONTINUE = 4;
constexpr int F_KEEPALIVE = 8;  // explicit "connection: keep-alive"

PyObject *http_error(int status, const char *msg) {
  PyObject *args = Py_BuildValue("(is)", status, msg);
  if (args) {
    PyErr_SetObject(PyExc_ValueError, args);
    Py_DECREF(args);
  }
  return nullptr;
}

inline char ascii_lower(char c) {
  return (c >= 'A' && c <= 'Z') ? char(c + 32) : c;
}

inline bool is_ows(char c) { return c == ' ' || c == '\t'; }

// strip optional whitespace in [b, e)
inline void strip_ows(const char *&b, const char *&e) {
  while (b < e && is_ows(*b)) ++b;
  while (e > b && is_ows(e[-1])) --e;
}

// case-insensitive equality against a lowercase literal; bounded by the
// literal's own terminator so network bytes containing NULs cannot walk
// past the end of the rodata string
bool ieq(const char *b, Py_ssize_t n, const char *lit) {
  Py_ssize_t i = 0;
  for (; i < n; ++i) {
    if (lit[i] == '\0' || ascii_lower(b[i]) != lit[i]) return false;
  }
  return lit[i] == '\0';
}

// parse(buffer, offset=0)
PyObject *parse(PyObject *, PyObject *args) {
  Py_buffer view;
  Py_ssize_t offset = 0;
  if (!PyArg_ParseTuple(args, "y*|n", &view, &offset)) return nullptr;
  const char *buf = static_cast<const char *>(view.buf);
  const Py_ssize_t len = view.len;
  PyObject *result = nullptr;

  do {
    if (offset < 0 || offset > len) {
      PyBuffer_Release(&view);
      return http_error(500, "bad offset");
    }
    const char *base = buf + offset;
    const Py_ssize_t n = len - offset;
    // locate end of head: CRLFCRLF
    const char *head_end = static_cast<const char *>(
        memmem(base, static_cast<size_t>(n), "\r\n\r\n", 4));
    if (!head_end) break;  // incomplete -> None
    const Py_ssize_t end = (head_end - base) + 4 + offset;

    // ---- request line ---------------------------------------------------
    const char *p = base;
    const char *line_end = static_cast<const char *>(
        memchr(p, '\r', static_cast<size_t>(head_end - p + 1)));
    if (!line_end) line_end = head_end;
    // bare CR is not a line terminator (RFC 9112 2.2) — treating it as one
    // while a peer parser doesn't is a request-smuggling differential
    if (line_end[1] != '\n') {
      PyBuffer_Release(&view);
      return http_error(400, "bare CR in request line");
    }
    const char *sp1 = static_cast<const char *>(
        memchr(p, ' ', static_cast<size_t>(line_end - p)));
    if (!sp1) {
      PyBuffer_Release(&view);
      return http_error(400, "malformed request line");
    }
    const char *sp2 = static_cast<const char *>(
        memchr(sp1 + 1, ' ', static_cast<size_t>(line_end - sp1 - 1)));
    if (!sp2 || static_cast<const char *>(memchr(
                    sp2 + 1, ' ', static_cast<size_t>(line_end - sp2 - 1)))) {
      PyBuffer_Release(&view);
      return http_error(400, "malformed request line");
    }
    // empty method/target reject BEFORE the version check — server.py
    // validates in that order, and the status must match on requests that
    // are invalid in multiple ways
    char method_buf[32];
    Py_ssize_t mlen = sp1 - p;
    if (mlen <= 0 || mlen > 31 || sp2 - sp1 <= 1) {
      PyBuffer_Release(&view);
      return http_error(400, "malformed request line");
    }
    for (Py_ssize_t i = 0; i < mlen; ++i) {
      char c = p[i];
      method_buf[i] = (c >= 'a' && c <= 'z') ? char(c - 32) : c;
    }

    // version: HTTP/1.<minor>
    const char *v = sp2 + 1;
    const Py_ssize_t vlen = line_end - v;
    if (vlen < 8 || memcmp(v, "HTTP/1.", 7) != 0) {
      PyBuffer_Release(&view);
      return http_error(505, "http version not supported");
    }
    int minor = 1;
    if (v[7] == '0' && vlen == 8) minor = 0;
    PyObject *method = PyUnicode_DecodeLatin1(method_buf, mlen, nullptr);
    PyObject *target = PyUnicode_DecodeLatin1(sp1 + 1, sp2 - sp1 - 1, nullptr);
    PyObject *headers = PyDict_New();
    if (!method || !target || !headers) {
      Py_XDECREF(method); Py_XDECREF(target); Py_XDECREF(headers);
      PyBuffer_Release(&view);
      return nullptr;
    }

    // ---- header lines ---------------------------------------------------
    Py_ssize_t content_length = -1;
    int flags = 0;
    bool bad = false;
    bool saw_cl = false, saw_te = false;
    int bad_status = 400;
    const char *bad_msg = "malformed header";
    p = (line_end < head_end) ? line_end + 2 : head_end;
    char keybuf[256];
    while (p < head_end && !bad) {
      const char *eol = static_cast<const char *>(
          memchr(p, '\r', static_cast<size_t>(head_end - p + 1)));
      if (!eol) eol = head_end;
      if (eol[1] != '\n') {  // bare CR inside a field line (RFC 9112 2.2)
        bad = true; bad_msg = "bare CR in header"; break;
      }
      if (eol == p) { p = eol + 2; continue; }  // empty line
      // obs-fold (RFC 7230 3.2.4): a continuation line would otherwise
      // parse as a fresh header and desync against proxies that unfold
      if (is_ows(*p)) { bad = true; bad_msg = "obsolete line folding"; break; }
      const char *colon = static_cast<const char *>(
          memchr(p, ':', static_cast<size_t>(eol - p)));
      if (!colon) { bad = true; break; }
      const char *kb = p, *ke = colon;
      strip_ows(kb, ke);
      const char *vb = colon + 1, *ve = eol;
      strip_ows(vb, ve);
      Py_ssize_t klen = ke - kb;
      if (klen <= 0 || klen > 255) { bad = true; break; }
      for (Py_ssize_t i = 0; i < klen; ++i) keybuf[i] = ascii_lower(kb[i]);

      // special-case the connection-management headers as we go
      if (klen == 14 && memcmp(keybuf, "content-length", 14) == 0) {
        Py_ssize_t cl = 0;
        bool overflow = false;
        if (vb == ve) { bad = true; bad_msg = "bad content-length"; break; }
        for (const char *q = vb; q < ve; ++q) {
          if (*q < '0' || *q > '9') {
            bad = true; bad_msg = "bad content-length"; break;
          }
          if (cl > MAX_BODY) overflow = true;  // clamp, keep validating digits
          else cl = cl * 10 + (*q - '0');
        }
        if (bad) break;
        Py_ssize_t parsed = overflow ? MAX_BODY + 1 : cl;
        // duplicate Content-Length with a different value is a smuggling
        // vector (proxies disagree on which wins) -> hard 400
        if (saw_cl && parsed != content_length) {
          bad = true; bad_msg = "conflicting content-length"; break;
        }
        saw_cl = true;
        // a numeric but oversized length is 413, not 400 (server.py parity)
        content_length = parsed;
      } else if (klen == 17 && memcmp(keybuf, "transfer-encoding", 17) == 0) {
        // RFC 7230 3.3.3: the FINAL coding must be chunked; anything else
        // (e.g. "gzip") would leave the body length undefined and lets a
        // front proxy frame the stream differently than we do -> 400
        saw_te = true;
        const char *lb = vb, *le = ve;  // last comma-separated token
        for (const char *q = ve; q > vb; --q) {
          if (q[-1] == ',') { lb = q; break; }
        }
        strip_ows(lb, le);
        if (le - lb == 7 && ieq(lb, 7, "chunked")) flags |= F_CHUNKED;
        else { bad = true; bad_msg = "unsupported transfer-encoding"; break; }
      } else if (klen == 10 && memcmp(keybuf, "connection", 10) == 0) {
        if (ieq(vb, ve - vb, "close")) flags |= F_CLOSE;
        else if (ieq(vb, ve - vb, "keep-alive")) flags |= F_KEEPALIVE;
      } else if (klen == 6 && memcmp(keybuf, "expect", 6) == 0) {
        if (ieq(vb, ve - vb, "100-continue")) flags |= F_EXPECT_CONTINUE;
      }

      PyObject *key = PyUnicode_DecodeLatin1(keybuf, klen, nullptr);
      PyObject *val = PyUnicode_DecodeLatin1(vb, ve - vb, nullptr);
      if (!key || !val || PyDict_SetItem(headers, key, val) < 0) {
        Py_XDECREF(key); Py_XDECREF(val);
        Py_DECREF(method); Py_DECREF(target); Py_DECREF(headers);
        PyBuffer_Release(&view);
        return nullptr;
      }
      Py_DECREF(key); Py_DECREF(val);
      p = eol + 2;
    }
    // Transfer-Encoding and Content-Length together is the canonical
    // request-smuggling ambiguity (RFC 7230 3.3.3 says TE wins, but
    // proxies differ) -> reject outright
    if (!bad && saw_te && saw_cl) {
      bad = true; bad_msg = "content-length with transfer-encoding";
    }
    if (bad) {
      Py_DECREF(method); Py_DECREF(target); Py_DECREF(headers);
      PyBuffer_Release(&view);
      return http_error(bad_status, bad_msg);
    }
    if (content_length > MAX_BODY) {
      Py_DECREF(method); Py_DECREF(target); Py_DECREF(headers);
      PyBuffer_Release(&view);
      return http_error(413, "body too large");
    }
    result = Py_BuildValue("(nNNiNni)", end, method, target, minor, headers,
                           content_length, flags);
  } while (false);

  PyBuffer_Release(&view);
  if (!result && !PyErr_Occurred()) Py_RETURN_NONE;
  return result;
}

// parse_chunked(buffer, offset) -> None | (body bytes, end)
PyObject *parse_chunked(PyObject *, PyObject *args) {
  Py_buffer view;
  Py_ssize_t offset = 0;
  if (!PyArg_ParseTuple(args, "y*|n", &view, &offset)) return nullptr;
  const char *buf = static_cast<const char *>(view.buf);
  const Py_ssize_t len = view.len;

  // first pass: walk chunks, compute total size; second: copy
  Py_ssize_t p = offset;
  Py_ssize_t total = 0;
  bool incomplete = false;
  // record (start, size) pairs in a small growable stack buffer
  Py_ssize_t static_spans[64][2];
  Py_ssize_t (*spans)[2] = static_spans;
  Py_ssize_t nspans = 0, cap_spans = 64;
  PyObject *result = nullptr;

  for (;;) {
    const char *nl = static_cast<const char *>(
        memmem(buf + p, static_cast<size_t>(len - p), "\r\n", 2));
    if (!nl) { incomplete = true; break; }
    // hex size, extensions after ';' ignored
    Py_ssize_t q = p;
    Py_ssize_t size = 0;
    bool any = false, badsize = false;
    for (; buf + q < nl; ++q) {
      char c = buf[q];
      int d;
      if (c >= '0' && c <= '9') d = c - '0';
      else if (c >= 'a' && c <= 'f') d = c - 'a' + 10;
      else if (c >= 'A' && c <= 'F') d = c - 'A' + 10;
      else if (c == ';') break;
      else { badsize = true; break; }
      size = size * 16 + d;
      any = true;
      if (size > MAX_BODY) { badsize = true; break; }
    }
    if (badsize || !any) {
      if (spans != static_spans) PyMem_Free(spans);
      PyBuffer_Release(&view);
      // valid hex that merely exceeds the cap is an oversized body (413,
      // server.py parity); non-hex garbage is a framing error (400)
      if (badsize && any && size > MAX_BODY)
        return http_error(413, "body too large");
      return http_error(400, "bad chunk size");
    }
    p = (nl - buf) + 2;
    if (size == 0) {
      // trailers until blank line
      for (;;) {
        const char *t = static_cast<const char *>(
            memmem(buf + p, static_cast<size_t>(len - p), "\r\n", 2));
        if (!t) { incomplete = true; break; }
        Py_ssize_t tl = t - (buf + p);
        p = (t - buf) + 2;
        if (tl == 0) break;  // blank line terminates trailers
      }
      break;
    }
    total += size;
    if (total > MAX_BODY) {
      if (spans != static_spans) PyMem_Free(spans);
      PyBuffer_Release(&view);
      return http_error(413, "body too large");
    }
    if (p + size + 2 > len) { incomplete = true; break; }
    if (nspans == cap_spans) {
      Py_ssize_t newcap = cap_spans * 2;
      Py_ssize_t (*ns)[2] = static_cast<Py_ssize_t (*)[2]>(
          PyMem_Malloc(sizeof(Py_ssize_t) * 2 * newcap));
      if (!ns) {
        if (spans != static_spans) PyMem_Free(spans);
        PyBuffer_Release(&view);
        return PyErr_NoMemory();
      }
      memcpy(ns, spans, sizeof(Py_ssize_t) * 2 * nspans);
      if (spans != static_spans) PyMem_Free(spans);
      spans = ns;
      cap_spans = newcap;
    }
    spans[nspans][0] = p;
    spans[nspans][1] = size;
    ++nspans;
    p += size;
    if (buf[p] != '\r' || buf[p + 1] != '\n') {
      if (spans != static_spans) PyMem_Free(spans);
      PyBuffer_Release(&view);
      return http_error(400, "bad chunk framing");
    }
    p += 2;
  }

  if (!incomplete) {
    result = PyBytes_FromStringAndSize(nullptr, total);
    if (result) {
      char *dst = PyBytes_AS_STRING(result);
      for (Py_ssize_t i = 0; i < nspans; ++i) {
        memcpy(dst, buf + spans[i][0], static_cast<size_t>(spans[i][1]));
        dst += spans[i][1];
      }
      PyObject *tup = Py_BuildValue("(Nn)", result, p);
      result = tup;  // tup owns body ref; nullptr on failure propagates
    }
  }
  if (spans != static_spans) PyMem_Free(spans);
  PyBuffer_Release(&view);
  if (!result && !PyErr_Occurred()) Py_RETURN_NONE;
  return result;
}

// parse_chunked_step(buffer, offset) -> (data bytes, new_offset, done)
//
// Incremental sibling of parse_chunked: consumes every COMPLETE chunk
// available from `offset` and returns their concatenated payload plus the
// resume offset. done=1 once the terminating 0-chunk AND its trailers are
// fully present (new_offset then points past the body). The protocol
// server keeps (offset, collected parts) across data_received calls so a
// large chunked upload is parsed once, not re-scanned per TCP segment
// (O(n) total instead of O(n^2)).
PyObject *parse_chunked_step(PyObject *, PyObject *args) {
  Py_buffer view;
  Py_ssize_t offset = 0;
  if (!PyArg_ParseTuple(args, "y*|n", &view, &offset)) return nullptr;
  const char *buf = static_cast<const char *>(view.buf);
  const Py_ssize_t len = view.len;

  Py_ssize_t p = offset;
  Py_ssize_t total = 0;
  int done = 0;
  Py_ssize_t static_spans[64][2];
  Py_ssize_t (*spans)[2] = static_spans;
  Py_ssize_t nspans = 0, cap_spans = 64;

  for (;;) {
    const Py_ssize_t chunk_start = p;
    const char *nl = static_cast<const char *>(
        memmem(buf + p, static_cast<size_t>(len - p), "\r\n", 2));
    if (!nl) break;  // size line incomplete -> resume at chunk_start
    Py_ssize_t q = p;
    Py_ssize_t size = 0;
    bool any = false, badsize = false;
    for (; buf + q < nl; ++q) {
      char c = buf[q];
      int d;
      if (c >= '0' && c <= '9') d = c - '0';
      else if (c >= 'a' && c <= 'f') d = c - 'a' + 10;
      else if (c >= 'A' && c <= 'F') d = c - 'A' + 10;
      else if (c == ';') break;
      else { badsize = true; break; }
      size = size * 16 + d;
      any = true;
      if (size > MAX_BODY) { badsize = true; break; }
    }
    if (badsize || !any) {
      if (spans != static_spans) PyMem_Free(spans);
      PyBuffer_Release(&view);
      if (badsize && any && size > MAX_BODY)
        return http_error(413, "body too large");
      return http_error(400, "bad chunk size");
    }
    p = (nl - buf) + 2;
    if (size == 0) {
      // trailers must be fully present to finish; else resume at the
      // 0-chunk so the next call re-examines it with more data
      bool trailers_done = false;
      Py_ssize_t tp = p;
      for (;;) {
        const char *t = static_cast<const char *>(
            memmem(buf + tp, static_cast<size_t>(len - tp), "\r\n", 2));
        if (!t) break;
        Py_ssize_t tl = t - (buf + tp);
        tp = (t - buf) + 2;
        if (tl == 0) { trailers_done = true; break; }
      }
      if (trailers_done) { p = tp; done = 1; }
      else p = chunk_start;
      break;
    }
    if (p + size + 2 > len) { p = chunk_start; break; }  // data incomplete
    if (nspans == cap_spans) {
      Py_ssize_t newcap = cap_spans * 2;
      Py_ssize_t (*ns)[2] = static_cast<Py_ssize_t (*)[2]>(
          PyMem_Malloc(sizeof(Py_ssize_t) * 2 * newcap));
      if (!ns) {
        if (spans != static_spans) PyMem_Free(spans);
        PyBuffer_Release(&view);
        return PyErr_NoMemory();
      }
      memcpy(ns, spans, sizeof(Py_ssize_t) * 2 * nspans);
      if (spans != static_spans) PyMem_Free(spans);
      spans = ns;
      cap_spans = newcap;
    }
    spans[nspans][0] = p;
    spans[nspans][1] = size;
    ++nspans;
    total += size;
    if (total > MAX_BODY) {
      if (spans != static_spans) PyMem_Free(spans);
      PyBuffer_Release(&view);
      return http_error(413, "body too large");
    }
    p += size;
    if (buf[p] != '\r' || buf[p + 1] != '\n') {
      if (spans != static_spans) PyMem_Free(spans);
      PyBuffer_Release(&view);
      return http_error(400, "bad chunk framing");
    }
    p += 2;
  }

  PyObject *data = PyBytes_FromStringAndSize(nullptr, total);
  PyObject *result = nullptr;
  if (data) {
    char *dst = PyBytes_AS_STRING(data);
    for (Py_ssize_t i = 0; i < nspans; ++i) {
      memcpy(dst, buf + spans[i][0], static_cast<size_t>(spans[i][1]));
      dst += spans[i][1];
    }
    result = Py_BuildValue("(Nni)", data, p, done);
  }
  if (spans != static_spans) PyMem_Free(spans);
  PyBuffer_Release(&view);
  return result;
}

const char *status_text(int s) {
  switch (s) {
    case 200: return "OK";
    case 201: return "Created";
    case 204: return "No Content";
    case 301: return "Moved Permanently";
    case 302: return "Found";
    case 304: return "Not Modified";
    case 400: return "Bad Request";
    case 401: return "Unauthorized";
    case 403: return "Forbidden";
    case 404: return "Not Found";
    case 405: return "Method Not Allowed";
    case 408: return "Request Timeout";
    case 409: return "Conflict";
    case 413: return "Payload Too Large";
    case 429: return "Too Many Requests";
    case 431: return "Request Header Fields Too Large";
    case 500: return "Internal Server Error";
    case 503: return "Service Unavailable";
    case 505: return "HTTP Version Not Supported";
    default: return "Status";
  }
}

// build_head(status, headers, content_length, close, chunked, body=None)
PyObject *build_head(PyObject *, PyObject *args) {
  int status, close_conn, chunked;
  PyObject *headers;        // sequence of (str, str)
  Py_ssize_t content_length;  // -1 = omit
  PyObject *body = Py_None;
  if (!PyArg_ParseTuple(args, "iOnii|O", &status, &headers, &content_length,
                        &close_conn, &chunked, &body))
    return nullptr;

  PyObject *seq = PySequence_Fast(headers, "headers must be a sequence");
  if (!seq) return nullptr;
  const Py_ssize_t nh = PySequence_Fast_GET_SIZE(seq);

  // measure pass
  size_t need = 64;  // status line + final CRLF slack
  bool has_cl = false, has_te = false;
  for (Py_ssize_t i = 0; i < nh; ++i) {
    PyObject *item = PySequence_Fast_GET_ITEM(seq, i);
    if (!PyTuple_Check(item) || PyTuple_GET_SIZE(item) != 2) {
      Py_DECREF(seq);
      PyErr_SetString(PyExc_TypeError, "header items must be 2-tuples");
      return nullptr;
    }
    Py_ssize_t kl, vl;
    const char *k = PyUnicode_AsUTF8AndSize(PyTuple_GET_ITEM(item, 0), &kl);
    const char *v = PyUnicode_AsUTF8AndSize(PyTuple_GET_ITEM(item, 1), &vl);
    if (!k || !v) { Py_DECREF(seq); return nullptr; }
    // CR/LF/NUL in a name or value would let a handler echoing untrusted
    // input split the response (Go's net/http sanitizes these too)
    for (Py_ssize_t j = 0; j < kl; ++j) {
      char c = k[j];
      if (c == '\r' || c == '\n' || c == '\0') {
        Py_DECREF(seq);
        PyErr_SetString(PyExc_ValueError, "invalid header name");
        return nullptr;
      }
    }
    for (Py_ssize_t j = 0; j < vl; ++j) {
      char c = v[j];
      if (c == '\r' || c == '\n' || c == '\0') {
        Py_DECREF(seq);
        PyErr_SetString(PyExc_ValueError, "invalid header value");
        return nullptr;
      }
    }
    need += size_t(kl) + size_t(vl) + 4;
    if (kl == 14 && ieq(k, 14, "content-length")) has_cl = true;
    if (kl == 17 && ieq(k, 17, "transfer-encoding")) has_te = true;
  }
  need += 48 /* content-length line */ + 48 /* te/conn lines */;
  const char *body_buf = nullptr;
  Py_ssize_t body_len = 0;
  if (body != Py_None) {
    if (PyBytes_Check(body)) {
      body_buf = PyBytes_AS_STRING(body);
      body_len = PyBytes_GET_SIZE(body);
    } else {
      Py_DECREF(seq);
      PyErr_SetString(PyExc_TypeError, "body must be bytes or None");
      return nullptr;
    }
    if (content_length < 0 && !chunked) content_length = body_len;
    need += size_t(body_len);
  }

  PyObject *out = PyBytes_FromStringAndSize(nullptr, Py_ssize_t(need));
  if (!out) { Py_DECREF(seq); return nullptr; }
  char *w = PyBytes_AS_STRING(out);
  char *w0 = w;
  w += snprintf(w, 64, "HTTP/1.1 %d %s\r\n", status, status_text(status));
  for (Py_ssize_t i = 0; i < nh; ++i) {
    PyObject *item = PySequence_Fast_GET_ITEM(seq, i);
    Py_ssize_t kl, vl;
    const char *k = PyUnicode_AsUTF8AndSize(PyTuple_GET_ITEM(item, 0), &kl);
    const char *v = PyUnicode_AsUTF8AndSize(PyTuple_GET_ITEM(item, 1), &vl);
    memcpy(w, k, size_t(kl)); w += kl;
    *w++ = ':'; *w++ = ' ';
    memcpy(w, v, size_t(vl)); w += vl;
    *w++ = '\r'; *w++ = '\n';
  }
  Py_DECREF(seq);
  if (close_conn) {
    memcpy(w, "Connection: close\r\n", 19); w += 19;
  }
  if (chunked && !has_te) {
    memcpy(w, "Transfer-Encoding: chunked\r\n", 28); w += 28;
  }
  if (!chunked && !has_cl && content_length >= 0) {
    // %zd of a 64-bit value can need 37 bytes incl. terminator; bound at
    // 48 (reserved above) so snprintf can never truncate and over-advance
    w += snprintf(w, 48, "Content-Length: %zd\r\n", content_length);
  }
  *w++ = '\r'; *w++ = '\n';
  if (body_buf && body_len) {
    memcpy(w, body_buf, size_t(body_len)); w += body_len;
  }
  if (_PyBytes_Resize(&out, w - w0) < 0) return nullptr;
  return out;
}

PyMethodDef methods[] = {
    {"parse", parse, METH_VARARGS,
     "parse(buf, offset=0) -> None | (end, method, target, minor, headers, "
     "content_length, flags)"},
    {"parse_chunked", parse_chunked, METH_VARARGS,
     "parse_chunked(buf, offset=0) -> None | (body, end)"},
    {"parse_chunked_step", parse_chunked_step, METH_VARARGS,
     "parse_chunked_step(buf, offset=0) -> (data, new_offset, done)"},
    {"build_head", build_head, METH_VARARGS,
     "build_head(status, headers, content_length, close, chunked, body=None) "
     "-> bytes"},
    {nullptr, nullptr, 0, nullptr},
};

PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_gofr_http",
    "Native HTTP/1.1 wire codec (see gofr_tpu/native/httpcore.cc)",
    -1, methods, nullptr, nullptr, nullptr, nullptr,
};

}  // namespace

PyMODINIT_FUNC PyInit__gofr_http(void) {
  PyObject *m = PyModule_Create(&moduledef);
  if (!m) return nullptr;
  PyModule_AddIntConstant(m, "F_CHUNKED", F_CHUNKED);
  PyModule_AddIntConstant(m, "F_CLOSE", F_CLOSE);
  PyModule_AddIntConstant(m, "F_EXPECT_CONTINUE", F_EXPECT_CONTINUE);
  PyModule_AddIntConstant(m, "F_KEEPALIVE", F_KEEPALIVE);
  return m;
}
