// _gofr_data: native batch assembly for the training data-loader.
//
// The loader's hot path gathers B shuffled fixed-length windows from a
// memory-mapped token file into one contiguous batch buffer every step.
// NumPy fancy indexing does this in C too, but holds the GIL and walks a
// generic take() path; this extension does straight-line memcpys with the
// GIL RELEASED, so batch assembly for step N+1 overlaps the device step N
// from the prefetch thread (gofr_tpu/data/__init__.py).
//
//   gather_windows(src, starts, window, itemsize, out) -> None
//     src:    buffer (the mmap'd token file, any 1-byte-addressable view)
//     starts: int64 C-contiguous array of ELEMENT offsets, one per row
//     window: elements per row
//     itemsize: bytes per element (2 or 4)
//     out:    writable buffer of len(starts) * window * itemsize bytes

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstring>

namespace {

PyObject *gather_windows(PyObject *, PyObject *args) {
  Py_buffer src, starts, out;
  Py_ssize_t window, itemsize;
  if (!PyArg_ParseTuple(args, "y*y*nny*", &src, &starts, &window, &itemsize,
                        &out))
    return nullptr;

  PyObject *err = nullptr;
  const Py_ssize_t n = starts.len / Py_ssize_t(sizeof(long long));
  const long long *idx = static_cast<const long long *>(starts.buf);
  // validate itemsize FIRST: the divisions below would SIGFPE on 0
  Py_ssize_t row_bytes = 0, src_elems = 0;

  if (itemsize != 2 && itemsize != 4) {
    PyErr_SetString(PyExc_ValueError, "itemsize must be 2 or 4");
    err = Py_None;
  } else if (window <= 0) {
    // a negative window would wrap the memcpy size to ~2^64 bytes
    PyErr_SetString(PyExc_ValueError, "window must be positive");
    err = Py_None;
  } else if (starts.len % Py_ssize_t(sizeof(long long)) != 0) {
    PyErr_SetString(PyExc_ValueError, "starts must be int64");
    err = Py_None;
  } else if (window > PY_SSIZE_T_MAX / itemsize) {
    PyErr_SetString(PyExc_ValueError, "window too large");
    err = Py_None;
  } else if ((row_bytes = window * itemsize,
              src_elems = src.len / itemsize,
              row_bytes > 0 && n > PY_SSIZE_T_MAX / row_bytes)) {
    // n * row_bytes below must not wrap
    PyErr_SetString(PyExc_ValueError, "batch too large");
    err = Py_None;
  } else if (out.len < n * row_bytes) {
    PyErr_SetString(PyExc_ValueError, "out buffer too small");
    err = Py_None;
  } else if (window > src_elems) {
    PyErr_SetString(PyExc_ValueError, "window exceeds source length");
    err = Py_None;
  } else {
    // bounds-check before dropping the GIL; phrased as idx > limit (not
    // idx + window > elems) so a hostile start offset cannot wrap int64
    for (Py_ssize_t i = 0; i < n; ++i) {
      if (idx[i] < 0 || idx[i] > (long long)(src_elems - window)) {
        PyErr_Format(PyExc_IndexError,
                     "window %zd at element %lld out of range (%zd elements)",
                     i, idx[i], src_elems);
        err = Py_None;
        break;
      }
    }
  }
  if (!err) {
    const char *s = static_cast<const char *>(src.buf);
    char *d = static_cast<char *>(out.buf);
    Py_BEGIN_ALLOW_THREADS
    for (Py_ssize_t i = 0; i < n; ++i) {
      memcpy(d + i * row_bytes, s + idx[i] * itemsize, size_t(row_bytes));
    }
    Py_END_ALLOW_THREADS
  }

  PyBuffer_Release(&src);
  PyBuffer_Release(&starts);
  PyBuffer_Release(&out);
  if (err) return nullptr;
  Py_RETURN_NONE;
}

PyMethodDef methods[] = {
    {"gather_windows", gather_windows, METH_VARARGS,
     "gather_windows(src, starts_int64, window, itemsize, out)"},
    {nullptr, nullptr, 0, nullptr},
};

PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_gofr_data",
    "Native batch gather for the token data-loader (datacore.cc)",
    -1, methods, nullptr, nullptr, nullptr, nullptr,
};

}  // namespace

PyMODINIT_FUNC PyInit__gofr_data(void) { return PyModule_Create(&moduledef); }
