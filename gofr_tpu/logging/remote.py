"""Remote dynamic log-level switching.

Parity: reference pkg/gofr/logging/remotelogger/dynamicLevelLogger.go:23-105 —
a wrapper that polls REMOTE_LOG_URL every REMOTE_LOG_FETCH_INTERVAL seconds
(default 15) and applies the returned level at runtime. Always installed by
the container (reference container.go:82-85); the poller only starts when a
URL is configured.

Expected response body: {"data": [{"serviceName": ..., "logLevel": "DEBUG"}]}
or simply {"logLevel": "DEBUG"} — we accept both.
"""

from __future__ import annotations

import json
import threading
import urllib.request

from . import Logger, level_from_string


class RemoteLevelLogger(Logger):
    def __init__(self, level: int, url: str | None, interval_s: float = 15.0, **kw):
        super().__init__(level=level, **kw)
        self._url = url
        self._interval = interval_s
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        if url:
            self._thread = threading.Thread(target=self._poll, daemon=True, name="gofr-remote-log-level")
            self._thread.start()

    def _fetch_level(self) -> int | None:
        assert self._url is not None
        with urllib.request.urlopen(self._url, timeout=5) as resp:  # noqa: S310
            body = json.loads(resp.read().decode("utf-8"))
        if isinstance(body, dict):
            data = body.get("data")
            if isinstance(data, list) and data and isinstance(data[0], dict):
                lvl = data[0].get("logLevel") or data[0].get("LOG_LEVEL")
                if lvl:
                    return level_from_string(lvl)
            lvl = body.get("logLevel") or body.get("LOG_LEVEL")
            if lvl:
                return level_from_string(lvl)
        return None

    def _poll(self) -> None:
        while not self._stop.wait(self._interval):
            try:
                lvl = self._fetch_level()
                if lvl is not None and lvl != self.level:
                    self.change_level(lvl)
            except Exception:  # noqa: BLE001 - poller must never die
                continue

    def close(self) -> None:
        self._stop.set()


def new(level_name: str | None, url: str | None, interval_s: float = 15.0) -> RemoteLevelLogger:
    return RemoteLevelLogger(level_from_string(level_name), url, interval_s)
