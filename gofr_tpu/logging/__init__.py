"""Structured leveled logging.

Parity: reference pkg/gofr/logging/ — levels DEBUG..FATAL (level.go:12-19),
JSON lines to stdout with ERROR+ to stderr (logger.go:54-82), terminal
auto-detect -> colorized pretty print with a PrettyPrint hook used by
request/SQL/Redis/pubsub/TPU logs (logger.go:17-19,146-160), file logger for
CMD apps (logger.go:177-196), mock logger for tests (mock_logger.go:15).

TPU-first notes: the logger is called from the asyncio event loop, gRPC
threadpool threads, and background pollers, so emission is a single atomic
``write`` of one pre-rendered line (no lock around user code).
"""

from __future__ import annotations

import io
import json
import os
import sys
import threading
import time
from typing import Any, Protocol, runtime_checkable

DEBUG, INFO, NOTICE, WARN, ERROR, FATAL = 1, 2, 3, 4, 5, 6

_LEVEL_NAMES = {DEBUG: "DEBUG", INFO: "INFO", NOTICE: "NOTICE", WARN: "WARN", ERROR: "ERROR", FATAL: "FATAL"}
_NAME_LEVELS = {v: k for k, v in _LEVEL_NAMES.items()}

# ANSI fg colors per level for terminal pretty mode.
_LEVEL_COLORS = {DEBUG: 36, INFO: 36, NOTICE: 36, WARN: 33, ERROR: 31, FATAL: 31}


def level_from_string(s: str | None) -> int:
    if not s:
        return INFO
    return _NAME_LEVELS.get(s.strip().upper(), INFO)


@runtime_checkable
class PrettyPrint(Protocol):
    """Log payloads implementing this render themselves in terminal mode.

    Parity: reference logging/logger.go:17-19 PrettyPrint interface.
    """

    def pretty_print(self, writer: io.TextIOBase) -> None: ...


class Logger:
    """Leveled logger. JSON lines in non-tty mode, colorized pretty in tty."""

    def __init__(
        self,
        level: int = INFO,
        out: Any = None,
        err: Any = None,
        pretty: bool | None = None,
    ):
        self.level = level
        self._out = out if out is not None else sys.stdout
        self._err = err if err is not None else sys.stderr
        if pretty is None:
            pretty = hasattr(self._out, "isatty") and self._out.isatty()
        self._pretty = pretty
        self._lock = threading.Lock()

    # -- level control (remote logger calls change_level at runtime) --
    def change_level(self, level: int) -> None:
        self.level = level

    # -- emission --
    def _log(self, level: int, args: tuple, kwargs: dict) -> None:
        if level < self.level:
            return
        stream = self._err if level >= ERROR else self._out
        t = time.time()
        if self._pretty:
            self._emit_pretty(stream, level, t, args, kwargs)
        else:
            self._emit_json(stream, level, t, args, kwargs)

    def _emit_json(self, stream, level: int, t: float, args: tuple, kwargs: dict) -> None:
        msg: Any
        if len(args) == 1:
            a = args[0]
            msg = a.to_log_dict() if hasattr(a, "to_log_dict") else a
        else:
            msg = " ".join(str(a) for a in args)
        rec = {
            "level": _LEVEL_NAMES[level],
            "time": time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime(t)) + f".{int((t % 1) * 1e6):06d}Z",
            "message": msg,
        }
        if kwargs:
            rec.update(kwargs)
        try:
            line = json.dumps(rec, default=str)
        except (TypeError, ValueError):
            rec["message"] = str(msg)
            line = json.dumps(rec, default=str)
        with self._lock:
            stream.write(line + "\n")

    def _emit_pretty(self, stream, level: int, t: float, args: tuple, kwargs: dict) -> None:
        color = _LEVEL_COLORS[level]
        ts = time.strftime("%H:%M:%S", time.localtime(t))
        prefix = f"\x1b[{color}m{_LEVEL_NAMES[level]:<6}\x1b[0m [{ts}] "
        buf = io.StringIO()
        buf.write(prefix)
        for a in args:
            if isinstance(a, PrettyPrint):
                a.pretty_print(buf)
            else:
                buf.write(str(a))
                buf.write(" ")
        if kwargs:
            buf.write(" ".join(f"{k}={v}" for k, v in kwargs.items()))
        buf.write("\n")
        with self._lock:
            stream.write(buf.getvalue())

    # -- public API --
    def debug(self, *args: Any, **kw: Any) -> None:
        self._log(DEBUG, args, kw)

    def info(self, *args: Any, **kw: Any) -> None:
        self._log(INFO, args, kw)

    def notice(self, *args: Any, **kw: Any) -> None:
        self._log(NOTICE, args, kw)

    def warn(self, *args: Any, **kw: Any) -> None:
        self._log(WARN, args, kw)

    warning = warn

    def error(self, *args: Any, **kw: Any) -> None:
        self._log(ERROR, args, kw)

    def fatal(self, *args: Any, **kw: Any) -> None:
        self._log(FATAL, args, kw)

    def logf(self, level: int, fmt: str, *args: Any) -> None:
        self._log(level, (fmt % args if args else fmt,), {})


def new_logger(level_name: str | None = None) -> Logger:
    return Logger(level=level_from_string(level_name))


def new_file_logger(path: str, level: int = INFO) -> Logger:
    """Logger writing to a file — used by CMD apps (reference logger.go:177-196)."""
    f = open(path, "a", encoding="utf-8")  # noqa: SIM115 - lifetime = process
    return Logger(level=level, out=f, err=f, pretty=False)


class MockLogger(Logger):
    """Captures log records for assertions. Parity: logging/mock_logger.go:15."""

    def __init__(self, level: int = DEBUG):
        self.records: list[tuple[int, tuple, dict]] = []
        super().__init__(level=level, out=io.StringIO(), err=io.StringIO(), pretty=False)

    def _log(self, level: int, args: tuple, kwargs: dict) -> None:
        if level >= self.level:
            self.records.append((level, args, kwargs))
        super()._log(level, args, kwargs)

    @property
    def stdout(self) -> str:
        return self._out.getvalue()

    @property
    def stderr(self) -> str:
        return self._err.getvalue()

    def messages(self, level: int | None = None) -> list[str]:
        return [
            " ".join(str(a) for a in args)
            for lvl, args, _ in self.records
            if level is None or lvl == level
        ]


def new_mock_logger(level: int = DEBUG) -> MockLogger:
    return MockLogger(level)
