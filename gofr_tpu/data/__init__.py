"""Training data-loader: memory-mapped token corpus → sharded, shuffled,
prefetched device batches with a checkpointable iterator.

The reference framework has no training pipeline (it is a web framework);
this fills the data-loader slot of the runtime inventory the TPU build
carries (SURVEY §2.9 resolution: native where the hot path warrants it).

Design:
- **Corpus = one flat token array on disk** (raw little-endian uint16/
  uint32, or a .npy of the same), memory-mapped — no parsing, no copies
  at open, OS page cache does the caching. `encode_corpus` writes it.
- **Sampling**: non-overlapping windows of seq_len+1 tokens (inputs and
  shifted targets come from one window), visited in a per-epoch
  deterministic permutation (Feistel-free: np.random.Generator(seed ^
  epoch) permutation of window indices).
- **Sharding**: `dp_rank`/`dp_size` stride the permuted windows, so data
  parallel ranks see disjoint streams with identical epoch boundaries —
  multi-host ready (each host passes its `jax.process_index()`).
- **Checkpoint/resume**: the iterator's `state()` is (epoch, step); a
  restored iterator replays the exact permutation position — training
  resumes mid-epoch without re-reading data (the aux-subsystem
  checkpoint/resume obligation, SURVEY §5).
- **Batch assembly** is the hot loop: B memcpys from the mmap into one
  contiguous array. The native `_gofr_data.gather_windows` does this with
  the GIL released (so the prefetch thread's assembly overlaps the device
  step); pure-NumPy fallback when the extension is unavailable.
- **Prefetch**: `device_prefetch` wraps any batch iterator with a
  lookahead thread that stages the next batch onto device (jax.device_put
  with an optional NamedSharding) while the current step runs.
"""

from __future__ import annotations

import dataclasses
import json
import os
import queue
import threading
from typing import Any, Iterator

import numpy as np

from ..native import load_data_core

__all__ = ["TokenDataset", "BatchIterator", "encode_corpus", "device_prefetch"]

_MAGIC = "gofr-tokens-v1"


def encode_corpus(tokens, path: str, *, vocab_size: int | None = None) -> str:
    """Write a token sequence as a raw mmap-able corpus + JSON sidecar.
    dtype is uint16 when the ids fit (vocab <= 65536), else uint32."""
    arr = np.asarray(tokens)
    amax = int(arr.max(initial=0))
    if arr.size and int(arr.min()) < 0:
        raise ValueError("token ids must be non-negative")
    if vocab_size is not None and amax >= vocab_size:
        raise ValueError(
            f"token id {amax} >= vocab_size {vocab_size} — astype would wrap silently"
        )
    hi = amax if vocab_size is None else vocab_size - 1
    dtype = np.uint16 if hi < 2**16 else np.uint32
    arr = arr.astype(dtype)
    with open(path, "wb") as f:
        f.write(arr.tobytes())
    with open(path + ".json", "w") as f:
        json.dump({"magic": _MAGIC, "dtype": arr.dtype.name, "n": int(arr.size)}, f)
    return path


@dataclasses.dataclass(frozen=True)
class _Meta:
    dtype: np.dtype
    n_tokens: int


def _open_corpus(path: str) -> tuple[_Meta, np.ndarray]:
    """Returns (meta, mmap'd 1-D token array) — one open per corpus."""
    sidecar = path + ".json"
    if os.path.exists(sidecar):
        with open(sidecar) as f:
            meta = json.load(f)
        if meta.get("magic") != _MAGIC:
            raise ValueError(f"{sidecar}: not a {_MAGIC} sidecar")
        m = _Meta(np.dtype(meta["dtype"]), int(meta["n"]))
        return m, np.memmap(path, dtype=m.dtype, mode="r")
    if path.endswith(".npy"):
        arr = np.load(path, mmap_mode="r")
        if arr.ndim != 1:
            raise ValueError("corpus .npy must be 1-D")
        return _Meta(arr.dtype, arr.size), arr
    raise FileNotFoundError(
        f"{path}: need a {sidecar} sidecar (use data.encode_corpus) or a .npy"
    )


class TokenDataset:
    """Memory-mapped token corpus serving fixed-length training windows."""

    def __init__(self, path: str, seq_len: int):
        self.path = path
        self.seq_len = seq_len
        meta, self._tokens = _open_corpus(path)
        self.dtype = meta.dtype
        self.n_tokens = meta.n_tokens
        # window = seq_len + 1 so (inputs, targets) shift out of one slice
        self.window = seq_len + 1
        self.n_windows = self.n_tokens // self.window
        if self.n_windows == 0:
            raise ValueError(
                f"corpus has {self.n_tokens} tokens < one window ({self.window})"
            )
        self._core = load_data_core()

    def batches(
        self,
        batch_size: int,
        *,
        seed: int = 0,
        dp_rank: int = 0,
        dp_size: int = 1,
        drop_remainder: bool = True,
    ) -> "BatchIterator":
        return BatchIterator(
            self, batch_size, seed=seed, dp_rank=dp_rank, dp_size=dp_size,
            drop_remainder=drop_remainder,
        )

    # -- hot path ---------------------------------------------------------
    def gather(self, window_ids: np.ndarray) -> np.ndarray:
        """[B] window indices -> [B, window] int32 batch."""
        starts = window_ids.astype(np.int64) * self.window
        if self._core is not None:
            out = np.empty((len(starts), self.window), self.dtype)
            self._core.gather_windows(
                memoryview(self._tokens).cast("B"),
                np.ascontiguousarray(starts),
                self.window,
                self.dtype.itemsize,
                memoryview(out).cast("B"),
            )
        else:
            out = self._tokens[starts[:, None] + np.arange(self.window)]
        return out.astype(np.int32)


class BatchIterator:
    """Deterministic, shardable, checkpointable batch stream.

    Yields dicts {"inputs": [B, seq_len], "targets": [B, seq_len]} int32.
    """

    def __init__(self, ds: TokenDataset, batch_size: int, *, seed: int,
                 dp_rank: int, dp_size: int, drop_remainder: bool):
        if not (0 <= dp_rank < dp_size):
            raise ValueError(f"dp_rank {dp_rank} not in [0, {dp_size})")
        self.ds = ds
        self.batch_size = batch_size
        self.seed = seed
        self.dp_rank = dp_rank
        self.dp_size = dp_size
        self.drop_remainder = drop_remainder
        self.epoch = 0
        self.step = 0
        self._perm: np.ndarray | None = None
        n_rank = (ds.n_windows - dp_rank + dp_size - 1) // dp_size
        if drop_remainder and n_rank < batch_size:
            raise ValueError(
                f"batch_size {batch_size} exceeds this rank's {n_rank} "
                f"windows/epoch (corpus too small for dp_size={dp_size} "
                f"with drop_remainder)"
            )

    # -- checkpoint/resume ------------------------------------------------
    def state(self) -> dict:
        """NOTE: when the iterator is wrapped in device_prefetch, the pump
        thread has advanced it PAST what the consumer has seen — snapshot
        with seek(consumed_batches) semantics instead (count batches in the
        training loop and call seek on resume), or checkpoint before
        wrapping."""
        return {"epoch": self.epoch, "step": self.step, "seed": self.seed,
                "dp_rank": self.dp_rank, "dp_size": self.dp_size,
                "batch_size": self.batch_size, "seq_len": self.ds.seq_len,
                "n_windows": self.ds.n_windows}

    def restore(self, state: dict) -> "BatchIterator":
        # position is step * batch_size within THIS rank's permutation of
        # THIS window grid — every one of these changes what it replays
        checks = {
            "seed": self.seed, "dp_size": self.dp_size,
            "dp_rank": self.dp_rank, "batch_size": self.batch_size,
            "seq_len": self.ds.seq_len, "n_windows": self.ds.n_windows,
        }
        for key, val in checks.items():
            if key in state and state[key] != val:
                raise ValueError(
                    f"restore: {key} mismatch (checkpoint {state[key]}, "
                    f"iterator {val})"
                )
        self.epoch = int(state["epoch"])
        self.step = int(state["step"])
        self._perm = None
        return self

    def seek(self, n_batches: int) -> "BatchIterator":
        """Position the stream as if n_batches had been consumed from the
        start — the prefetch-safe resume: the training loop checkpoints its
        own consumed count, not the (look-ahead-advanced) iterator."""
        spe = self.steps_per_epoch()
        self.epoch, self.step = divmod(int(n_batches), spe)
        self._perm = None
        return self

    # -- iteration --------------------------------------------------------
    def _epoch_perm(self) -> np.ndarray:
        if self._perm is None:
            rng = np.random.default_rng((self.seed, self.epoch))
            perm = rng.permutation(self.ds.n_windows)
            self._perm = perm[self.dp_rank :: self.dp_size]
        return self._perm

    def steps_per_epoch(self) -> int:
        n = len(self._epoch_perm()) if self._perm is not None else (
            (self.ds.n_windows - self.dp_rank + self.dp_size - 1) // self.dp_size
        )
        return n // self.batch_size if self.drop_remainder else (
            (n + self.batch_size - 1) // self.batch_size
        )

    def __iter__(self) -> Iterator[dict]:
        return self

    def __next__(self) -> dict:
        perm = self._epoch_perm()
        lo = self.step * self.batch_size
        if lo + (self.batch_size if self.drop_remainder else 1) > len(perm):
            # epoch rollover — the stream is infinite; epoch boundaries are
            # visible through .state()/.epoch
            self.epoch += 1
            self.step = 0
            self._perm = None
            perm = self._epoch_perm()
            lo = 0
        ids = perm[lo : lo + self.batch_size]
        self.step += 1
        batch = self.ds.gather(ids)
        return {"inputs": batch[:, :-1], "targets": batch[:, 1:]}


def device_prefetch(it, *, lookahead: int = 2, sharding: Any = None):
    """Wrap a batch iterator: a background thread stages `lookahead`
    batches onto device (jax.device_put, optionally with a NamedSharding)
    while the consumer runs the current step. Batch assembly (native
    gather, GIL-free) and h2d overlap device compute."""
    import jax

    q: queue.Queue = queue.Queue(maxsize=lookahead)
    stop = threading.Event()
    done = object()  # end-of-stream sentinel: a finite iterator must
    # surface StopIteration, not deadlock the consumer's q.get()

    def pump():
        try:
            for batch in it:
                if stop.is_set():
                    return
                staged = (
                    jax.device_put(batch, sharding)
                    if sharding is not None
                    else jax.device_put(batch)
                )
                q.put(staged)
            q.put(done)
        except BaseException as e:  # noqa: BLE001 - surfaced to consumer
            q.put(e)

    t = threading.Thread(target=pump, daemon=True, name="gofr-data-prefetch")
    t.start()

    class _Prefetched:
        def __iter__(self):
            return self

        def __next__(self):
            item = q.get()
            if item is done:
                raise StopIteration
            if isinstance(item, BaseException):
                raise item
            return item

        def close(self):
            stop.set()
            try:
                q.get_nowait()  # unblock a full queue
            except queue.Empty:
                pass

    return _Prefetched()
