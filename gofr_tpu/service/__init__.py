"""Outbound HTTP service client.

Parity: reference pkg/gofr/service/ — NewHTTPService(addr, logger, metrics,
options...) (new.go:68-89), every verb funneling through one instrumented
request path: span, traceparent injection, app_http_service_response
histogram, structured log (new.go:135-195); decorator Options pattern
(options.go:3-5); circuit breaker with open/closed states + background
health probes (circuit_breaker.go:24-158); auth decorators (basic_auth.go,
apikey_auth.go, oauth.go); custom default health endpoint
(health_config.go:5-24); health feeding the container aggregate
(health.go:18-49).

Transport: urllib over a thread (stdlib; no aiohttp in this image). Async
handlers await the a* methods; sync handlers call get/post/... directly.
"""

from __future__ import annotations

import asyncio
import base64
import json as jsonlib
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, Callable

from ..datasource import STATUS_DOWN, STATUS_UP, health

__all__ = [
    "HTTPService",
    "new_http_service",
    "Response",
    "BasicAuth",
    "APIKeyAuth",
    "OAuth",
    "CustomHeaders",
    "HealthConfig",
    "TLSConfig",
    "CircuitBreaker",
    "CircuitOpenError",
]


class Response:
    def __init__(self, status: int, headers: dict, body: bytes):
        self.status_code = status
        self.headers = headers
        self.body = body

    def json(self) -> Any:
        return jsonlib.loads(self.body)

    def text(self) -> str:
        return self.body.decode("utf-8", "replace")


class CircuitOpenError(Exception):
    def __init__(self, address: str):
        super().__init__(f"circuit breaker open for {address}")

    def status_code(self) -> int:
        return 503


class HTTPService:
    """Core client; options decorate it (options.go pattern: each option's
    apply() mutates/wraps behavior)."""

    def __init__(self, address: str, logger=None, metrics=None, tracer=None):
        self.address = address.rstrip("/")
        self.logger = logger
        self.metrics = metrics
        self.tracer = tracer
        self.static_headers: dict[str, str] = {}
        self.auth_header: Callable[[], dict[str, str]] | None = None
        self.health_endpoint = ".well-known/alive"
        self.circuit: CircuitBreaker | None = None
        # TLS for https addresses: None uses urllib's default verification;
        # an ssl.SSLContext (e.g. with a private CA) overrides it — the
        # reference's TLSConfig seam on its http.Client (service/new.go:68-89)
        self.tls_context = None

    # -- request path (new.go:135-195) ------------------------------------
    def _headers(self, headers: dict | None) -> dict:
        out = dict(self.static_headers)
        if self.auth_header is not None:
            out.update(self.auth_header())
        if headers:
            out.update(headers)
        # traceparent injection (new.go:158)
        try:
            from ..tracing import current_span

            span = current_span()
            if span is not None:
                out.setdefault(
                    "traceparent", f"00-{span.trace_id}-{span.span_id}-01"
                )
        except Exception:  # noqa: BLE001
            pass
        return out

    def request(
        self,
        method: str,
        path: str,
        *,
        params: dict | None = None,
        json: Any = None,
        body: bytes | None = None,
        headers: dict | None = None,
        timeout: float = 10.0,
        _health_probe: bool = False,
    ) -> Response:
        if self.circuit is not None and not _health_probe:
            self.circuit.precheck(self)
        url = f"{self.address}/{path.lstrip('/')}"
        if params:
            url += "?" + urllib.parse.urlencode(params)
        data = jsonlib.dumps(json).encode() if json is not None else body
        hdrs = self._headers(headers)
        if json is not None:
            hdrs.setdefault("Content-Type", "application/json")
        req = urllib.request.Request(url, method=method, data=data, headers=hdrs)
        t0 = time.perf_counter()
        status = 0
        try:
            with urllib.request.urlopen(
                req, timeout=timeout, context=self.tls_context
            ) as resp:
                out = Response(resp.status, dict(resp.headers), resp.read())
        except urllib.error.HTTPError as e:
            out = Response(e.code, dict(e.headers), e.read())
        except Exception:
            if self.circuit is not None and not _health_probe:
                self.circuit.record_failure(self)
            self._observe(method, path, 0, t0)
            raise
        status = out.status_code
        if self.circuit is not None and not _health_probe:
            if status >= 500:
                self.circuit.record_failure(self)
            else:
                self.circuit.record_success()
        self._observe(method, path, status, t0)
        return out

    def _observe(self, method: str, path: str, status: int, t0: float) -> None:
        dt = time.perf_counter() - t0
        if self.metrics is not None:
            self.metrics.record_histogram(
                "app_http_service_response", dt,
                path=path, method=method, status=str(status),
            )
        if self.logger is not None:
            self.logger.debug(
                {
                    "type": "http-service", "method": method,
                    "uri": f"{self.address}/{path.lstrip('/')}",
                    "response_code": status,
                    "response_time_us": round(dt * 1e6),
                }
            )

    # -- verbs ------------------------------------------------------------
    def get(self, path: str, **kw) -> Response:
        return self.request("GET", path, **kw)

    def post(self, path: str, **kw) -> Response:
        return self.request("POST", path, **kw)

    def put(self, path: str, **kw) -> Response:
        return self.request("PUT", path, **kw)

    def patch(self, path: str, **kw) -> Response:
        return self.request("PATCH", path, **kw)

    def delete(self, path: str, **kw) -> Response:
        return self.request("DELETE", path, **kw)

    # -- async facades ----------------------------------------------------
    async def aget(self, path: str, **kw) -> Response:
        return await asyncio.get_running_loop().run_in_executor(
            None, lambda: self.get(path, **kw)
        )

    async def apost(self, path: str, **kw) -> Response:
        return await asyncio.get_running_loop().run_in_executor(
            None, lambda: self.post(path, **kw)
        )

    async def aput(self, path: str, **kw) -> Response:
        return await asyncio.get_running_loop().run_in_executor(
            None, lambda: self.put(path, **kw)
        )

    async def adelete(self, path: str, **kw) -> Response:
        return await asyncio.get_running_loop().run_in_executor(
            None, lambda: self.delete(path, **kw)
        )

    # -- health (service/health.go:18-49) ----------------------------------
    def health_check_sync(self) -> dict:
        try:
            t0 = time.perf_counter()
            resp = self.request("GET", self.health_endpoint, timeout=5.0, _health_probe=True)
            ok = resp.status_code < 400
            return health(
                STATUS_UP if ok else STATUS_DOWN,
                host=self.address,
                status_code=resp.status_code,
                latency_ms=round((time.perf_counter() - t0) * 1e3, 2),
                **(
                    {"circuit": self.circuit.state}
                    if self.circuit is not None
                    else {}
                ),
            )
        except Exception as e:  # noqa: BLE001
            return health(STATUS_DOWN, host=self.address, error=str(e))


# -- options (decorator pattern, options.go) --------------------------------


class BasicAuth:
    def __init__(self, user: str, password: str):
        self.user, self.password = user, password

    def apply(self, svc: HTTPService) -> None:
        token = base64.b64encode(f"{self.user}:{self.password}".encode()).decode()
        svc.auth_header = lambda: {"Authorization": f"Basic {token}"}


class APIKeyAuth:
    def __init__(self, key: str):
        self.key = key

    def apply(self, svc: HTTPService) -> None:
        svc.auth_header = lambda: {"X-API-KEY": self.key}


class OAuth:
    """Client-credentials flow (oauth.go:233-...): fetch + cache a bearer
    token from token_url, refresh when expired."""

    def __init__(self, client_id: str, client_secret: str, token_url: str, scopes: list[str] | None = None):
        self.client_id = client_id
        self.client_secret = client_secret
        self.token_url = token_url
        self.scopes = scopes or []
        self._token: str | None = None
        self._expiry = 0.0
        self._lock = threading.Lock()

    def _fetch(self) -> None:
        data = urllib.parse.urlencode(
            {
                "grant_type": "client_credentials",
                "client_id": self.client_id,
                "client_secret": self.client_secret,
                **({"scope": " ".join(self.scopes)} if self.scopes else {}),
            }
        ).encode()
        req = urllib.request.Request(self.token_url, method="POST", data=data)
        with urllib.request.urlopen(req, timeout=10) as resp:
            payload = jsonlib.loads(resp.read())
        self._token = payload["access_token"]
        self._expiry = time.time() + float(payload.get("expires_in", 3600)) - 30

    def token(self) -> str:
        with self._lock:
            if self._token is None or time.time() >= self._expiry:
                self._fetch()
            assert self._token is not None
            return self._token

    def apply(self, svc: HTTPService) -> None:
        svc.auth_header = lambda: {"Authorization": f"Bearer {self.token()}"}


class CustomHeaders:
    def __init__(self, headers: dict[str, str]):
        self.headers = headers

    def apply(self, svc: HTTPService) -> None:
        svc.static_headers.update(self.headers)


class HealthConfig:
    def __init__(self, endpoint: str):
        self.endpoint = endpoint

    def apply(self, svc: HTTPService) -> None:
        svc.health_endpoint = endpoint_strip(self.endpoint)


class TLSConfig:
    """Option: TLS settings for https addresses — a ready SSLContext, a
    private CA bundle, or (dev only) verification off. Mirrors the
    reference's TLSConfig on its http.Client (service/new.go:68-89)."""

    def __init__(self, context=None, *, ca_cert: str | None = None,
                 insecure: bool = False):
        import ssl

        if context is None:
            context = ssl.create_default_context(cafile=ca_cert)
            if insecure:
                context.check_hostname = False
                context.verify_mode = ssl.CERT_NONE
        self.context = context

    def apply(self, svc: HTTPService) -> None:
        svc.tls_context = self.context


def endpoint_strip(e: str) -> str:
    return e.lstrip("/")


class CircuitBreaker:
    """Open after `threshold` consecutive 5xx/transport failures; while open,
    requests fail fast with CircuitOpenError and a background thread probes
    the health endpoint every `interval` seconds, closing on success
    (circuit_breaker.go:24-158)."""

    def __init__(self, threshold: int = 5, interval: float = 10.0):
        self.threshold = threshold
        self.interval = interval
        self.failures = 0
        self.state = "closed"
        self._lock = threading.Lock()
        self._probe_thread: threading.Thread | None = None

    def apply(self, svc: HTTPService) -> None:
        svc.circuit = self

    def precheck(self, svc: HTTPService) -> None:
        with self._lock:
            if self.state == "open":
                raise CircuitOpenError(svc.address)

    def record_success(self) -> None:
        with self._lock:
            self.failures = 0
            self.state = "closed"

    def record_failure(self, svc: HTTPService) -> None:
        with self._lock:
            self.failures += 1
            if self.failures >= self.threshold and self.state != "open":
                self.state = "open"
                self._start_probe(svc)

    def _start_probe(self, svc: HTTPService) -> None:
        if self._probe_thread is not None and self._probe_thread.is_alive():
            return

        def probe():
            while True:
                time.sleep(self.interval)
                with self._lock:
                    if self.state != "open":
                        return
                h = svc.health_check_sync()
                if h["status"] == STATUS_UP:
                    self.record_success()
                    if svc.logger is not None:
                        svc.logger.info(f"circuit closed for {svc.address}")
                    return

        self._probe_thread = threading.Thread(target=probe, daemon=True)
        self._probe_thread.start()


def new_http_service(address: str, logger=None, metrics=None, *options, tracer=None) -> HTTPService:
    """NewHTTPService (new.go:68-89): construct + apply option decorators."""
    if metrics is not None:
        from ..metrics import HTTP_BUCKETS

        metrics.new_histogram(
            "app_http_service_response", "outbound http call time s", HTTP_BUCKETS
        )
    svc = HTTPService(address, logger, metrics, tracer)
    for opt in options:
        opt.apply(svc)
    return svc
