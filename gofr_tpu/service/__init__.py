"""Outbound HTTP service client.

Parity: reference pkg/gofr/service/ — NewHTTPService(addr, logger, metrics,
options...) (new.go:68-89), every verb funneling through one instrumented
request path: span, traceparent injection, app_http_service_response
histogram, structured log (new.go:135-195); decorator Options pattern
(options.go:3-5); circuit breaker with open/closed states + background
health probes (circuit_breaker.go:24-158); auth decorators (basic_auth.go,
apikey_auth.go, oauth.go); custom default health endpoint
(health_config.go:5-24); health feeding the container aggregate
(health.go:18-49).

Transport: urllib over a thread (stdlib; no aiohttp in this image). Async
handlers await the a* methods; sync handlers call get/post/... directly.
"""

from __future__ import annotations

import asyncio
import base64
import json as jsonlib
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, AsyncIterator, Callable

from ..datasource import STATUS_DOWN, STATUS_UP, health

__all__ = [
    "HTTPService",
    "new_http_service",
    "Response",
    "ServiceStream",
    "BasicAuth",
    "APIKeyAuth",
    "OAuth",
    "CustomHeaders",
    "HealthConfig",
    "TLSConfig",
    "CircuitBreaker",
    "CircuitOpenError",
]


class Response:
    def __init__(self, status: int, headers: dict, body: bytes):
        self.status_code = status
        self.headers = headers
        self.body = body

    def json(self) -> Any:
        return jsonlib.loads(self.body)

    def text(self) -> str:
        return self.body.decode("utf-8", "replace")


class CircuitOpenError(Exception):
    def __init__(self, address: str):
        super().__init__(f"circuit breaker open for {address}")

    def status_code(self) -> int:
        return 503


class _AsyncConnPool:
    """Keep-alive connection pool for the streaming client (:meth:`HTTPService.astream`).

    One pool per service (one upstream address). Idle ``(reader, writer)``
    pairs are stacked LIFO — the hottest connection is reused first, so a
    steady request stream runs on O(concurrency) sockets instead of a
    dial per request (a per-request TCP+TLS handshake would dominate the
    hop cost of a proxy tier; docs/advanced-guide/scale-out.md). Pairs are
    loop-bound: asyncio streams only work on the loop that created them,
    so a pool observed from a different running loop is flushed rather
    than handing out unusable sockets (multi-loop apps each re-dial).

    ``hits``/``dials`` counters verify reuse (the router exports them as
    ``app_http_service_conn_pool_total``).
    """

    def __init__(self, max_idle: int = 64, idle_ttl_s: float = 60.0):
        self.max_idle = max_idle
        self.idle_ttl_s = idle_ttl_s
        self._idle: list[tuple] = []  # (reader, writer, t_idle)
        self._loop = None
        self.hits = 0
        self.dials = 0

    def _flush(self) -> None:
        for _r, w, _t in self._idle:
            try:
                w.close()
            except Exception:  # noqa: BLE001 — teardown
                pass
        self._idle.clear()

    def acquire(self):
        """Pop a live idle pair for the CURRENT loop, or None (caller
        dials). Never blocks."""
        loop = asyncio.get_running_loop()
        if self._loop is not loop:
            self._flush()
            self._loop = loop
            return None
        now = time.monotonic()
        while self._idle:
            reader, writer, t = self._idle.pop()
            if now - t > self.idle_ttl_s or reader.at_eof() or (
                writer.transport is None or writer.transport.is_closing()
            ):
                try:
                    writer.close()
                except Exception:  # noqa: BLE001
                    pass
                continue
            return reader, writer
        return None

    def release(self, reader, writer) -> None:
        if (
            self._loop is not asyncio.get_running_loop()
            or len(self._idle) >= self.max_idle
            or reader.at_eof()
            or writer.transport is None
            or writer.transport.is_closing()
        ):
            try:
                writer.close()
            except Exception:  # noqa: BLE001
                pass
            return
        self._idle.append((reader, writer, time.monotonic()))

    def close(self) -> None:
        """Close idle sockets, from any thread. asyncio transports may
        only be touched from their owning loop, so a cross-thread close
        (the fleet poll thread reaping a backend) marshals the flush
        onto the pool's loop; a closed loop's transports died with it."""
        loop = self._loop
        try:
            running = asyncio.get_running_loop()
        except RuntimeError:
            running = None
        if loop is None or loop is running:
            self._flush()
            return
        idle, self._idle = self._idle, []

        def _close_all() -> None:
            for _r, w, _t in idle:
                try:
                    w.close()
                except Exception:  # noqa: BLE001 — teardown
                    pass

        try:
            loop.call_soon_threadsafe(_close_all)
        except RuntimeError:  # loop already closed
            pass

    def stats(self) -> dict:
        return {
            "idle": len(self._idle), "hits": self.hits, "dials": self.dials,
        }


class ServiceStream:
    """One in-flight streamed exchange from :meth:`HTTPService.astream`:
    status/headers up front, body chunks as the upstream produces them.

    The connection returns to the keep-alive pool only when the body is
    read to completion; :meth:`aclose` before that point ABORTS the
    socket — which is exactly the disconnect signal a streaming LLM
    backend needs to cancel the abandoned generation (the PR 9
    client-disconnect contract crossing the router hop)."""

    def __init__(self, svc: "HTTPService", reader, writer, status: int,
                 headers: dict[str, str], *, method: str, reused: bool,
                 timeout: float):
        self._svc = svc
        self._reader = reader
        self._writer = writer
        self.status_code = status
        self.headers = headers
        self.reused = reused
        self._timeout = timeout
        self._method = method
        self._done = False
        self._closed = False
        te = headers.get("transfer-encoding", "").lower()
        self._chunked = "chunked" in te
        cl = headers.get("content-length", "")
        self._remaining = int(cl) if cl.isdigit() else None
        if method == "HEAD" or status in (204, 304):
            self._chunked = False
            self._remaining = 0
        # reusable: HTTP/1.1 keep-alive with a delimited body
        self._reusable = (
            headers.get("connection", "").lower() != "close"
            and (self._chunked or self._remaining is not None)
        )

    @property
    def streamed(self) -> bool:
        """True when the upstream did not pre-commit a length — the
        proxy must forward chunk-by-chunk rather than buffer."""
        return self._chunked

    async def _read(self, coro):
        return await asyncio.wait_for(coro, timeout=self._timeout)

    async def aiter_raw(self, max_chunk: int = 65536) -> AsyncIterator[bytes]:
        """Yield body bytes as the upstream produces them (chunked
        framing decoded). Releases the connection to the pool at EOF."""
        try:
            if self._chunked:
                while True:
                    size_line = await self._read(self._reader.readline())
                    hexpart = size_line.strip().split(b";")[0]
                    if not hexpart:
                        raise ConnectionError("bad chunk size from upstream")
                    size = int(hexpart, 16)
                    if size == 0:
                        while (await self._read(self._reader.readline())).strip():
                            pass  # trailers
                        break
                    while size > 0:
                        data = await self._read(
                            self._reader.read(min(size, max_chunk))
                        )
                        if not data:
                            raise ConnectionError("upstream closed mid-chunk")
                        size -= len(data)
                        yield data
                    await self._read(self._reader.readexactly(2))  # CRLF
            elif self._remaining is not None:
                while self._remaining > 0:
                    data = await self._read(
                        self._reader.read(min(self._remaining, max_chunk))
                    )
                    if not data:
                        raise ConnectionError("upstream closed mid-body")
                    self._remaining -= len(data)
                    yield data
            else:  # close-delimited: read to EOF, connection not reusable
                while True:
                    data = await self._read(self._reader.read(max_chunk))
                    if not data:
                        break
                    yield data
            self._done = True
        finally:
            await self.aclose()

    async def aread(self) -> bytes:
        return b"".join([c async for c in self.aiter_raw()])

    async def aclose(self) -> None:
        """Release (body fully read) or abort (mid-body) the connection.
        Idempotent — the proxy's finally path and aiter_raw's EOF path
        both land here."""
        if self._closed:
            return
        self._closed = True
        if self._done and self._reusable:
            self._svc._pool.release(self._reader, self._writer)
        else:
            try:
                self._writer.close()
            except Exception:  # noqa: BLE001 — teardown must not mask
                pass


class HTTPService:
    """Core client; options decorate it (options.go pattern: each option's
    apply() mutates/wraps behavior)."""

    def __init__(self, address: str, logger=None, metrics=None, tracer=None):
        self.address = address.rstrip("/")
        self.logger = logger
        self.metrics = metrics
        self.tracer = tracer
        self._pool = _AsyncConnPool()
        self.static_headers: dict[str, str] = {}
        self.auth_header: Callable[[], dict[str, str]] | None = None
        self.health_endpoint = ".well-known/alive"
        self.circuit: CircuitBreaker | None = None
        # TLS for https addresses: None uses urllib's default verification;
        # an ssl.SSLContext (e.g. with a private CA) overrides it — the
        # reference's TLSConfig seam on its http.Client (service/new.go:68-89)
        self.tls_context = None

    # -- request path (new.go:135-195) ------------------------------------
    def _headers(self, headers: dict | None) -> dict:
        out = dict(self.static_headers)
        if self.auth_header is not None:
            out.update(self.auth_header())
        if headers:
            out.update(headers)
        # traceparent injection (new.go:158)
        try:
            from ..tracing import current_span

            span = current_span()
            if span is not None:
                out.setdefault(
                    "traceparent", f"00-{span.trace_id}-{span.span_id}-01"
                )
        except Exception:  # noqa: BLE001
            pass
        return out

    def request(
        self,
        method: str,
        path: str,
        *,
        params: dict | None = None,
        json: Any = None,
        body: bytes | None = None,
        headers: dict | None = None,
        timeout: float = 10.0,
        _health_probe: bool = False,
    ) -> Response:
        if self.circuit is not None and not _health_probe:
            self.circuit.precheck(self)
        url = f"{self.address}/{path.lstrip('/')}"
        if params:
            url += "?" + urllib.parse.urlencode(params)
        data = jsonlib.dumps(json).encode() if json is not None else body
        hdrs = self._headers(headers)
        if json is not None:
            hdrs.setdefault("Content-Type", "application/json")
        req = urllib.request.Request(url, method=method, data=data, headers=hdrs)
        t0 = time.perf_counter()
        status = 0
        try:
            with urllib.request.urlopen(
                req, timeout=timeout, context=self.tls_context
            ) as resp:
                out = Response(resp.status, dict(resp.headers), resp.read())
        except urllib.error.HTTPError as e:
            out = Response(e.code, dict(e.headers), e.read())
        except Exception:
            if self.circuit is not None and not _health_probe:
                self.circuit.record_failure(self)
            self._observe(method, path, 0, t0)
            raise
        status = out.status_code
        if self.circuit is not None and not _health_probe:
            if status >= 500:
                self.circuit.record_failure(self)
            else:
                self.circuit.record_success()
        self._observe(method, path, status, t0)
        return out

    def _observe(self, method: str, path: str, status: int, t0: float) -> None:
        dt = time.perf_counter() - t0
        if self.metrics is not None:
            self.metrics.record_histogram(
                "app_http_service_response", dt,
                path=path, method=method, status=str(status),
            )
        if self.logger is not None:
            self.logger.debug(
                {
                    "type": "http-service", "method": method,
                    "uri": f"{self.address}/{path.lstrip('/')}",
                    "response_code": status,
                    "response_time_us": round(dt * 1e6),
                }
            )

    # -- verbs ------------------------------------------------------------
    def get(self, path: str, **kw) -> Response:
        return self.request("GET", path, **kw)

    def post(self, path: str, **kw) -> Response:
        return self.request("POST", path, **kw)

    def put(self, path: str, **kw) -> Response:
        return self.request("PUT", path, **kw)

    def patch(self, path: str, **kw) -> Response:
        return self.request("PATCH", path, **kw)

    def delete(self, path: str, **kw) -> Response:
        return self.request("DELETE", path, **kw)

    # -- async facades ----------------------------------------------------
    async def aget(self, path: str, **kw) -> Response:
        return await asyncio.get_running_loop().run_in_executor(
            None, lambda: self.get(path, **kw)
        )

    async def apost(self, path: str, **kw) -> Response:
        return await asyncio.get_running_loop().run_in_executor(
            None, lambda: self.post(path, **kw)
        )

    async def aput(self, path: str, **kw) -> Response:
        return await asyncio.get_running_loop().run_in_executor(
            None, lambda: self.put(path, **kw)
        )

    async def adelete(self, path: str, **kw) -> Response:
        return await asyncio.get_running_loop().run_in_executor(
            None, lambda: self.delete(path, **kw)
        )

    # -- pooled keep-alive streaming (docs/advanced-guide/scale-out.md) ----
    def pool_stats(self) -> dict:
        return self._pool.stats()

    def close(self) -> None:
        """Drop pooled keep-alive sockets and stop the breaker's probe
        loop. Safe from any thread — the pool marshals transport
        teardown onto its owning loop."""
        self._pool.close()
        if self.circuit is not None:
            self.circuit.close()

    def _hostport(self) -> tuple[str, int, bool]:
        parts = urllib.parse.urlsplit(self.address)
        tls = parts.scheme == "https"
        return parts.hostname or "", parts.port or (443 if tls else 80), tls

    async def _dial(self, timeout: float):
        host, port, tls = self._hostport()
        ssl_ctx = None
        if tls:
            import ssl

            ssl_ctx = self.tls_context or ssl.create_default_context()
        return await asyncio.wait_for(
            asyncio.open_connection(host, port, ssl=ssl_ctx), timeout=timeout
        )

    def _count_pool(self, result: str) -> None:
        if self.metrics is not None:
            self.metrics.increment_counter(
                "app_http_service_conn_pool_total",
                result=result, address=self.address,
            )

    async def astream(
        self,
        method: str,
        path: str,
        *,
        params: dict | None = None,
        json: Any = None,
        body: bytes | None = None,
        headers: dict | None = None,
        timeout: float = 30.0,
        metric_path: str | None = None,
    ) -> ServiceStream:
        """Asyncio-native request over a pooled keep-alive connection,
        returning status+headers as soon as the upstream sends them and
        the body as a chunk stream (:class:`ServiceStream`).

        This is the streaming/proxy hot path: unlike the urllib verbs it
        never parks a thread per in-flight request (a proxy tier carries
        thousands), reuses pooled sockets (``pool_stats()`` /
        ``app_http_service_conn_pool_total`` verify), and hands the
        caller the unread body so chunks can be forwarded as they
        arrive. Circuit breaker, traceparent injection, and the
        app_http_service_response histogram behave exactly like
        :meth:`request`. A reused socket that turns out stale (upstream
        closed it while idle) is redialed once, transparently."""
        if self.circuit is not None:
            self.circuit.precheck(self)
        target = "/" + path.lstrip("/")
        if params:
            target += "?" + urllib.parse.urlencode(params)
        # histogram label: `path` here may be CLIENT-controlled (the
        # router proxies the inbound target verbatim) — as a metric
        # label every distinct URL+query would mint a new series, an
        # unbounded-cardinality leak any scanner can drive. Callers
        # with attacker-reachable paths pass a fixed metric_path; the
        # query is stripped for everyone else.
        mpath = metric_path if metric_path is not None else path.split("?", 1)[0]
        data = jsonlib.dumps(json).encode() if json is not None else body
        hdrs = self._headers(headers)
        if json is not None:
            hdrs.setdefault("Content-Type", "application/json")
        host, port, _tls = self._hostport()
        t0 = time.perf_counter()
        pooled = self._pool.acquire()
        attempt_reuse = pooled is not None
        try:
            if pooled is None:
                pooled = await self._dial(timeout)
                self._pool.dials += 1
                self._count_pool("dial")
            else:
                self._pool.hits += 1
                self._count_pool("hit")
            try:
                stream = await self._exchange(
                    pooled, method, target, hdrs, data,
                    host=f"{host}:{port}", timeout=timeout,
                    reused=attempt_reuse,
                )
            except (ConnectionError, asyncio.IncompleteReadError) as e:
                # NOT OSError: on 3.11+ TimeoutError subclasses OSError,
                # and a response-header timeout is a SLOW backend, not a
                # stale socket — re-sending a non-idempotent request
                # there would run the work twice. Likewise any PARTIAL
                # response bytes prove the backend accepted the request
                # and began work before the connection died mid-reply.
                partial = getattr(e, "partial", b"")
                if isinstance(e, TimeoutError) or partial or not attempt_reuse:
                    raise
                # stale keep-alive socket: redial once and retry whole-
                # request (nothing of the response had arrived, and the
                # request body is bytes, so the resend is identical)
                try:
                    pooled[1].close()
                except Exception:  # noqa: BLE001
                    pass
                pooled = await self._dial(timeout)
                self._pool.dials += 1
                self._count_pool("dial")
                stream = await self._exchange(
                    pooled, method, target, hdrs, data,
                    host=f"{host}:{port}", timeout=timeout, reused=False,
                )
        except CircuitOpenError:
            raise
        except Exception:
            # a failed exchange must not leak the socket: the transport
            # would stay registered with the loop (fd build-up against a
            # sick backend), and the upstream would never see the
            # disconnect — an abandoned generation would decode to
            # completion behind a timeout.
            if pooled is not None:
                try:
                    pooled[1].close()
                except Exception:  # noqa: BLE001 — teardown
                    pass
            if self.circuit is not None:
                self.circuit.record_failure(self)
            self._observe(method, mpath, 0, t0)
            raise
        if self.circuit is not None:
            if stream.status_code >= 500:
                self.circuit.record_failure(self)
            else:
                self.circuit.record_success()
        self._observe(method, mpath, stream.status_code, t0)
        return stream

    async def _exchange(
        self, pooled, method: str, target: str, hdrs: dict, data: bytes | None,
        *, host: str, timeout: float, reused: bool,
    ) -> ServiceStream:
        reader, writer = pooled
        head = [f"{method} {target} HTTP/1.1\r\nHost: {host}\r\n"]
        lower = {k.lower() for k in hdrs}
        if "content-length" not in lower:
            head.append(f"Content-Length: {len(data) if data else 0}\r\n")
        for k, v in hdrs.items():
            head.append(f"{k}: {v}\r\n")
        head.append("\r\n")
        writer.write("".join(head).encode("latin-1") + (data or b""))
        await asyncio.wait_for(writer.drain(), timeout=timeout)
        block = await asyncio.wait_for(
            reader.readuntil(b"\r\n\r\n"), timeout=timeout
        )
        lines = block.decode("latin-1").split("\r\n")
        status_parts = lines[0].split(" ", 2)
        if len(status_parts) < 2 or not status_parts[1].isdigit():
            raise ConnectionError(f"malformed status line: {lines[0]!r}")
        status = int(status_parts[1])
        resp_headers: dict[str, str] = {}
        for line in lines[1:]:
            if not line or ":" not in line:
                continue
            k, _, v = line.partition(":")
            resp_headers[k.strip().lower()] = v.strip()
        if not status_parts[0].startswith("HTTP/1.1"):
            resp_headers.setdefault("connection", "close")
        return ServiceStream(
            self, reader, writer, status, resp_headers,
            method=method, reused=reused, timeout=timeout,
        )

    # -- health (service/health.go:18-49) ----------------------------------
    def health_check_sync(self) -> dict:
        try:
            t0 = time.perf_counter()
            resp = self.request("GET", self.health_endpoint, timeout=5.0, _health_probe=True)
            ok = resp.status_code < 400
            return health(
                STATUS_UP if ok else STATUS_DOWN,
                host=self.address,
                status_code=resp.status_code,
                latency_ms=round((time.perf_counter() - t0) * 1e3, 2),
                **(
                    {"circuit": self.circuit.state}
                    if self.circuit is not None
                    else {}
                ),
            )
        except Exception as e:  # noqa: BLE001
            return health(STATUS_DOWN, host=self.address, error=str(e))


# -- options (decorator pattern, options.go) --------------------------------


class BasicAuth:
    def __init__(self, user: str, password: str):
        self.user, self.password = user, password

    def apply(self, svc: HTTPService) -> None:
        token = base64.b64encode(f"{self.user}:{self.password}".encode()).decode()
        svc.auth_header = lambda: {"Authorization": f"Basic {token}"}


class APIKeyAuth:
    def __init__(self, key: str):
        self.key = key

    def apply(self, svc: HTTPService) -> None:
        svc.auth_header = lambda: {"X-API-KEY": self.key}


class OAuth:
    """Client-credentials flow (oauth.go:233-...): fetch + cache a bearer
    token from token_url, refresh when expired."""

    def __init__(self, client_id: str, client_secret: str, token_url: str, scopes: list[str] | None = None):
        self.client_id = client_id
        self.client_secret = client_secret
        self.token_url = token_url
        self.scopes = scopes or []
        self._token: str | None = None
        self._expiry = 0.0
        self._lock = threading.Lock()

    def _fetch(self) -> None:
        data = urllib.parse.urlencode(
            {
                "grant_type": "client_credentials",
                "client_id": self.client_id,
                "client_secret": self.client_secret,
                **({"scope": " ".join(self.scopes)} if self.scopes else {}),
            }
        ).encode()
        req = urllib.request.Request(self.token_url, method="POST", data=data)
        with urllib.request.urlopen(req, timeout=10) as resp:
            payload = jsonlib.loads(resp.read())
        self._token = payload["access_token"]
        self._expiry = time.time() + float(payload.get("expires_in", 3600)) - 30

    def token(self) -> str:
        with self._lock:
            if self._token is None or time.time() >= self._expiry:
                self._fetch()
            assert self._token is not None
            return self._token

    def apply(self, svc: HTTPService) -> None:
        svc.auth_header = lambda: {"Authorization": f"Bearer {self.token()}"}


class CustomHeaders:
    def __init__(self, headers: dict[str, str]):
        self.headers = headers

    def apply(self, svc: HTTPService) -> None:
        svc.static_headers.update(self.headers)


class HealthConfig:
    def __init__(self, endpoint: str):
        self.endpoint = endpoint

    def apply(self, svc: HTTPService) -> None:
        svc.health_endpoint = endpoint_strip(self.endpoint)


class TLSConfig:
    """Option: TLS settings for https addresses — a ready SSLContext, a
    private CA bundle, or (dev only) verification off. Mirrors the
    reference's TLSConfig on its http.Client (service/new.go:68-89)."""

    def __init__(self, context=None, *, ca_cert: str | None = None,
                 insecure: bool = False):
        import ssl

        if context is None:
            context = ssl.create_default_context(cafile=ca_cert)
            if insecure:
                context.check_hostname = False
                context.verify_mode = ssl.CERT_NONE
        self.context = context

    def apply(self, svc: HTTPService) -> None:
        svc.tls_context = self.context


def endpoint_strip(e: str) -> str:
    return e.lstrip("/")


class CircuitBreaker:
    """Open after `threshold` consecutive 5xx/transport failures; while open,
    requests fail fast with CircuitOpenError and a background thread probes
    the health endpoint every `interval` seconds, closing on success
    (circuit_breaker.go:24-158)."""

    def __init__(self, threshold: int = 5, interval: float = 10.0):
        self.threshold = threshold
        self.interval = interval
        self.failures = 0
        self.state = "closed"
        self._lock = threading.Lock()
        self._probe_thread: threading.Thread | None = None
        self._closed = False

    def close(self) -> None:
        """Stop the probe loop: a breaker whose service was torn down
        (the scale-out router removing a reaped backend) must not keep
        dialing a dead address forever."""
        with self._lock:
            self._closed = True

    def apply(self, svc: HTTPService) -> None:
        svc.circuit = self

    def precheck(self, svc: HTTPService) -> None:
        with self._lock:
            if self.state == "open":
                raise CircuitOpenError(svc.address)

    def record_success(self) -> None:
        with self._lock:
            self.failures = 0
            self.state = "closed"

    def record_failure(self, svc: HTTPService) -> None:
        with self._lock:
            self.failures += 1
            if self.failures >= self.threshold and self.state != "open":
                self.state = "open"
                self._start_probe(svc)

    def _start_probe(self, svc: HTTPService) -> None:
        if self._probe_thread is not None and self._probe_thread.is_alive():
            return

        def probe():
            while True:
                time.sleep(self.interval)
                with self._lock:
                    if self._closed or self.state != "open":
                        return
                h = svc.health_check_sync()
                if h["status"] == STATUS_UP:
                    self.record_success()
                    if svc.logger is not None:
                        svc.logger.info(f"circuit closed for {svc.address}")
                    return

        self._probe_thread = threading.Thread(target=probe, daemon=True)
        self._probe_thread.start()


def new_http_service(address: str, logger=None, metrics=None, *options, tracer=None) -> HTTPService:
    """NewHTTPService (new.go:68-89): construct + apply option decorators."""
    if metrics is not None:
        from ..metrics import HTTP_BUCKETS

        metrics.new_histogram(
            "app_http_service_response", "outbound http call time s", HTTP_BUCKETS
        )
        metrics.new_counter(
            "app_http_service_conn_pool_total",
            "streaming-path connections by result (hit=keep-alive reuse, dial=new socket)",
        )
    svc = HTTPService(address, logger, metrics, tracer)
    for opt in options:
        opt.apply(svc)
    return svc
