"""App: the composition root.

Parity: reference pkg/gofr/gofr.go — New()/NewCMD() (gofr.go:64,101), Run()
(gofr.go:116), HTTP verbs (gofr.go:234-256), Subscribe (gofr.go:384),
Migrate (gofr.go:281), AddCronJob (gofr.go:414), AddRESTHandlers
(gofr.go:394), AddHTTPService (gofr.go:221), auth enablement
(gofr.go:348-382), UseMiddleware (gofr.go:408), Shutdown (gofr.go:182),
well-known route registration (gofr.go:137-150).

Default ports (reference default.go:3-7): HTTP 8000, gRPC 9000, metrics 2121.
"""

from __future__ import annotations

import asyncio
import signal
import threading
from typing import Any, Callable

from .config import Config, EnvConfig
from .container import Container
from .context import Context
from .handler import (
    debug_blackbox_handler,
    debug_compiles_handler,
    debug_engine_handler,
    debug_profile_handler,
    debug_traces_handler,
    debug_usage_handler,
    favicon_wire_handler,
    health_handler,
    live_handler,
    replay_handler,
    rollout_handler,
    rollout_status_handler,
    wrap_handler,
)
from .http.middleware import (
    apikey_auth_middleware,
    basic_auth_middleware,
    cors_middleware,
    logging_middleware,
    metrics_middleware,
    oauth_middleware,
    tracer_middleware,
)
from .http.router import Router
from .http.server import AsyncHTTPServer
from .metrics.server import MetricsServer
from .tracing import new_tracer


class App:
    def __init__(self, config: Config | None = None, configs_dir: str = "./configs"):
        self.config: Config = config if config is not None else EnvConfig(configs_dir)
        self.container = Container.create(self.config)
        self.logger = self.container.logger
        self.tracer = new_tracer(self.config, self.logger)
        self.container.tracer = self.tracer  # type: ignore[attr-defined]

        self.http_port = self.config.get_int("HTTP_PORT", 8000)
        self.grpc_port = self.config.get_int("GRPC_PORT", 9000)
        self.metrics_port = self.config.get_int("METRICS_PORT", 2121)
        self.request_timeout = self.config.get_float("REQUEST_TIMEOUT", 5.0)

        self.router = Router()
        # Default chain, reference order (router.go:23-28): Tracer -> Logging -> CORS -> Metrics
        self.router.use(tracer_middleware(self.tracer))
        self.router.use(logging_middleware(self.logger))
        self.router.use(cors_middleware(self._cors_overrides()))
        self.router.use(metrics_middleware(self.container.metrics))

        self.http_server = self._make_http_server()
        self.metrics_server = MetricsServer(self.container.metrics, self.metrics_port)
        self.grpc_server = None  # created on first register_service
        self._grpc_registered = False

        self._subscriptions: dict[str, Callable] = {}
        self._bg_factories: list[Callable] = []  # add_background_task
        self._cron = None
        self._static_dirs: list[tuple[str, str]] = []
        self._route_registered = False
        self._shutdown_event: asyncio.Event | None = None
        self._loop: asyncio.AbstractEventLoop | None = None
        self._bg_tasks: list[asyncio.Task] = []
        # graceful drain (rolling deploys; docs/advanced-guide/resilience.md):
        # the flag lives on the CONTAINER so handlers (health readiness)
        # see it without a back-reference to the app
        self._draining = False
        self.container.draining = False
        self.drain_deadline_s = self.config.get_float("GOFR_DRAIN_DEADLINE_S", 30.0)

    def _make_http_server(self):
        """Native-codec protocol server when the C++ extension builds
        (gofr_tpu/native), pure-Python asyncio streams server otherwise.
        GOFR_HTTP_NATIVE=0 forces the fallback; both pass the same
        conformance suite (tests/test_native_http.py)."""
        tls = self._server_tls()
        if self.config.get_or_default("GOFR_HTTP_NATIVE", "1") != "0":
            from .http.nativeserver import NativeHTTPServer

            if NativeHTTPServer.available():
                return NativeHTTPServer(
                    self.router.dispatch, self.http_port, logger=self.logger,
                    tls=tls,
                )
            self.logger.warn(
                "native HTTP codec unavailable; using pure-Python server"
            )
        return AsyncHTTPServer(
            self.router.dispatch, self.http_port, logger=self.logger, tls=tls
        )

    def _server_tls(self):
        """Optional HTTPS: HTTP_TLS_CERT_FILE + HTTP_TLS_KEY_FILE PEM
        paths (the reference terminates TLS at the ingress instead)."""
        cert = self.config.get("HTTP_TLS_CERT_FILE")
        key = self.config.get("HTTP_TLS_KEY_FILE")
        if not cert or not key:
            return None
        import ssl

        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.load_cert_chain(cert, key)
        return ctx

    def _cors_overrides(self) -> dict[str, str]:
        """ACCESS_CONTROL_ALLOW_* env overrides -> header names."""
        out = {}
        for key in ("ACCESS_CONTROL_ALLOW_ORIGIN", "ACCESS_CONTROL_ALLOW_HEADERS", "ACCESS_CONTROL_ALLOW_CREDENTIALS"):
            v = self.config.get(key)
            if v:
                header = "-".join(w.capitalize() for w in key.split("_"))
                out[header] = v
        return out

    # ---- route registration (gofr.go:234-256) ----
    def _add(
        self, method: str, path: str, handler: Callable,
        timeout_s: float | None = None,
    ) -> None:
        self._route_registered = True
        self.router.add(
            method, path,
            wrap_handler(
                handler, self.container,
                timeout_s if timeout_s is not None else self.request_timeout,
            ),
        )

    def get(self, path: str, handler: Callable) -> None:
        self._add("GET", path, handler)

    def post(self, path: str, handler: Callable) -> None:
        self._add("POST", path, handler)

    def put(self, path: str, handler: Callable) -> None:
        self._add("PUT", path, handler)

    def patch(self, path: str, handler: Callable) -> None:
        self._add("PATCH", path, handler)

    def delete(self, path: str, handler: Callable) -> None:
        self._add("DELETE", path, handler)

    def use_middleware(self, *mws) -> None:
        for mw in mws:
            self.router.use(mw)

    # ---- auth (gofr.go:348-382) ----
    def enable_basic_auth(self, *user_pass: str) -> None:
        if len(user_pass) % 2 != 0:
            self.logger.warn("enable_basic_auth: odd argument count; ignoring trailing username")
        users = dict(zip(user_pass[::2], user_pass[1::2]))
        self.router.use(basic_auth_middleware(users=users))

    def enable_basic_auth_with_func(self, validate_func) -> None:
        self.router.use(basic_auth_middleware(validate_func=validate_func))

    def enable_api_key_auth(self, *keys: str) -> None:
        self.router.use(apikey_auth_middleware(keys=list(keys)))

    def enable_api_key_auth_with_func(self, validate_func) -> None:
        self.router.use(apikey_auth_middleware(validate_func=validate_func))

    def enable_oauth(self, jwks_url: str, refresh_interval_s: float = 300.0) -> None:
        from .http.middleware.auth import JWKSProvider

        self.router.use(oauth_middleware(JWKSProvider(jwks_url, refresh_interval_s)))

    # ---- outbound services (gofr.go:221) ----
    def add_http_service(self, name: str, address: str, *options) -> None:
        from .service import new_http_service

        if name in self.container.services:
            self.logger.warn(f"service {name} already registered, overwriting")
        self.container.services[name] = new_http_service(
            address, self.logger, self.container.metrics, *options
        )

    # ---- TPU models (the build's ctx.TPU() registry) ----
    def register_model(self, name: str, *args, **kwargs):
        return self.container.tpu().register_model(name, *args, **kwargs)

    # ---- pub/sub (gofr.go:384-392) ----
    def subscribe(self, topic: str, handler: Callable) -> None:
        if self.container.pubsub is None:
            self.logger.error("subscriber not initialized in the container (set PUBSUB_BACKEND)")
            return
        self._subscriptions[topic] = handler

    # ---- background tasks (the batch tier's drain loop rides this) ----
    def add_background_task(self, coro_factory: Callable) -> None:
        """Schedule ``coro_factory()`` as a long-lived task on the app
        loop at serve() time (cancelled at shutdown, like subscriber
        loops). The factory is called on the serving loop — pass the
        coroutine FUNCTION, not a coroutine object, so a restart of
        serve() gets a fresh coroutine."""
        self._bg_factories.append(coro_factory)

    # ---- cron (gofr.go:414) ----
    def add_cron_job(self, schedule: str, job_name: str, job: Callable) -> None:
        from .cron import Cron

        if self._cron is None:
            self._cron = Cron(self.container)
        self._cron.add_job(schedule, job_name, job)

    # ---- migrations (gofr.go:281) ----
    def migrate(self, migrations: dict[int, Any]) -> None:
        from .migration import run as run_migrations

        try:
            run_migrations(migrations, self.container)
        except Exception as e:  # noqa: BLE001 - parity: panic-recovery wrap (gofr.go:283)
            self.logger.error(f"migration failed: {e!r}")
            raise

    # ---- external DB injection (externalDB.go:5-12) ----
    def add_mongo(self, provider) -> None:
        """Wire a user-constructed Mongo provider: the framework injects
        logger + metrics, connects it, and exposes it as ctx.mongo."""
        self.container.add_mongo(provider)

    # ---- CRUD (gofr.go:394) ----
    def add_rest_handlers(self, entity_cls) -> None:
        from .crud import register_crud_handlers

        register_crud_handlers(self, entity_cls)

    # ---- gRPC (gofr.go:57-61) ----
    def _ensure_grpc(self):
        from .grpcx import GRPCServer

        if self.grpc_server is None:
            self.grpc_server = GRPCServer(self.container, self.grpc_port, self.tracer)
        return self.grpc_server

    def register_service(self, add_servicer_fn, servicer) -> None:
        """add_servicer_fn: generated add_XServicer_to_server(servicer, server)."""
        self._ensure_grpc().register(add_servicer_fn, servicer)
        self._grpc_registered = True  # only after successful registration

    def grpc_unary(self, service: str, method: str, handler: Callable) -> None:
        """Framework-native RPC: handler(ctx) -> result, JSON over gRPC —
        the same handler shape as HTTP (fixes the reference's Context
        asymmetry, SURVEY.md §3.6)."""
        self._ensure_grpc().add_unary(service, method, handler)
        self._grpc_registered = True

    def grpc_server_stream(self, service: str, method: str, handler: Callable) -> None:
        """handler(ctx) -> iterator of chunks (sync generator, async
        generator, or coroutine returning an iterable) — e.g. decoded
        tokens."""
        self._ensure_grpc().add_server_stream(service, method, handler)
        self._grpc_registered = True

    # ---- static files + swagger ----
    def add_static_files(self, route: str, directory: str) -> None:
        self._static_dirs.append((route, directory))

    # ---- run / shutdown (gofr.go:116-202) ----
    def _register_well_known(self) -> None:
        self.get("/.well-known/health", health_handler)
        self.get("/.well-known/alive", live_handler)
        self.get("/.well-known/debug/engine", debug_engine_handler)
        self.get("/.well-known/debug/compiles", debug_compiles_handler)
        # Journey ring shard read (the fleet stitcher's fan-out target;
        # docs/advanced-guide/observability-serving.md#request-journeys)
        self.get("/.well-known/debug/traces", debug_traces_handler)
        # The profile route gets its own timeout budget: a capture costs
        # its window (<=30 s) plus ~10 s of one-time profiler init, which
        # must not be bounded by the API-SLO REQUEST_TIMEOUT (default 5 s).
        self._add(
            "POST", "/.well-known/debug/profile", debug_profile_handler,
            timeout_s=max(60.0, self.request_timeout),
        )
        self._add("POST", "/.well-known/debug/drain", self._drain_handler)
        # Model lifecycle (docs/advanced-guide/rollouts.md): GET = the
        # per-model version/rollout view; POST stages a zero-downtime
        # weight rollout from a checkpoint path. The POST gets its own
        # timeout budget — loading a multi-GB checkpoint host-side can
        # exceed the API-SLO REQUEST_TIMEOUT; the shift itself runs on
        # the controller thread and the route returns immediately after
        # staging. Loopback-only unless GOFR_ROLLOUT_REMOTE=1 (the
        # drain route's trust model: this swaps the serving weights).
        self.get("/.well-known/debug/rollout", rollout_status_handler)
        self._add(
            "POST", "/.well-known/debug/rollout", rollout_handler,
            timeout_s=max(120.0, self.request_timeout),
        )
        # Incident flight recorder (docs/advanced-guide/
        # incident-debugging.md): GET lists this process's black-box
        # bundles + recorder state (the router fans it fleet-wide);
        # POST replays a flight record. The replay gets its own timeout
        # budget — it re-decodes the recorded emission on the serving
        # chips, which the API-SLO REQUEST_TIMEOUT must not bound.
        # Loopback-only unless GOFR_REPLAY_REMOTE=1.
        # The front router binds its FLEET-FAN variant to this path at
        # build time; the per-process built-in must yield to it (the
        # well-known block runs late, at serve()).
        if not self.router.has("GET", "/.well-known/debug/blackbox"):
            self.get("/.well-known/debug/blackbox", debug_blackbox_handler)
        # Per-tenant usage metering / chargeback export (gofr_tpu.goodput;
        # docs/advanced-guide/cost-accounting.md). Same yield-to-router
        # discipline: the front router binds its fleet-fan variant here.
        if not self.router.has("GET", "/.well-known/debug/usage"):
            self.get("/.well-known/debug/usage", debug_usage_handler)
        self._add(
            "POST", "/.well-known/debug/replay", replay_handler,
            timeout_s=max(120.0, self.request_timeout),
        )
        self.router.add("GET", "/favicon.ico", favicon_wire_handler)
        from .swagger import register_swagger_routes

        register_swagger_routes(self)
        for route, directory in self._static_dirs:
            from .staticfiles import register_static_route

            register_static_route(self, route, directory)

    async def serve(self) -> None:
        """Start all servers and block until shutdown() (gofr.go:116-178)."""
        self._loop = asyncio.get_running_loop()
        self._shutdown_event = asyncio.Event()
        self._register_well_known()
        self.router.build()

        self.metrics_server.start()
        self.logger.info(f"Starting metrics server on :{self.metrics_server.port}")
        await self.http_server.start()

        if self._grpc_registered and self.grpc_server is not None:
            self.grpc_server.start()
            self.logger.info(f"gRPC server listening on :{self.grpc_server.port}")

        for topic, handler in self._subscriptions.items():
            self._bg_tasks.append(asyncio.ensure_future(self._run_subscriber(topic, handler)))

        for factory in self._bg_factories:
            self._bg_tasks.append(asyncio.ensure_future(factory()))

        if self._cron is not None:
            self._bg_tasks.append(asyncio.ensure_future(self._cron.run()))

        tpu = self.container.tpu_runtime
        if tpu is not None:
            await tpu.start_batchers()

        await self._shutdown_event.wait()
        await self._stop_servers()

    async def _run_subscriber(self, topic: str, handler: Callable) -> None:
        """Per-topic subscription loop (subscriber.go:27-57): receive ->
        Context -> handler -> commit on success, with panic recovery."""
        from .datasource.pubsub import SubscribeContextRequest

        pubsub = self.container.pubsub
        assert pubsub is not None
        while self._shutdown_event is not None and not self._shutdown_event.is_set():
            try:
                msg = await pubsub.subscribe(topic)
            except asyncio.CancelledError:
                return
            except Exception as e:  # noqa: BLE001
                self.logger.error(f"error while reading from topic {topic}: {e!r}")
                await asyncio.sleep(1.0)
                continue
            if msg is None:
                continue
            self.container.metrics.increment_counter("app_pubsub_subscribe_total_count", topic=topic)
            ctx = Context(SubscribeContextRequest(msg), self.container)
            try:
                if asyncio.iscoroutinefunction(handler):
                    err = await handler(ctx)
                else:
                    err = await asyncio.get_running_loop().run_in_executor(None, handler, ctx)
            except Exception as e:  # noqa: BLE001 - panic recovery (subscriber.go:46)
                self.logger.error(f"error in subscriber handler for {topic}: {e!r}")
                continue
            if err is not None:
                # Handler signaled failure by returning an error: do NOT
                # commit, so the message is redelivered (subscriber.go:50-55).
                self.logger.error(f"subscriber handler for {topic} returned error: {err!r}")
                continue
            msg.commit()
            self.container.metrics.increment_counter("app_pubsub_subscribe_success_count", topic=topic)

    async def _stop_servers(self) -> None:
        for t in self._bg_tasks:
            t.cancel()
        await self.http_server.shutdown()
        if self.grpc_server is not None:
            self.grpc_server.shutdown()
        self.metrics_server.shutdown()
        tpu = self.container.tpu_runtime
        if tpu is not None:
            await tpu.stop_batchers()
        self.tracer.shutdown()
        self.container.close()
        self.logger.info("Server shutdown complete")

    def shutdown(self) -> None:
        if self._loop is not None and self._shutdown_event is not None:
            self._loop.call_soon_threadsafe(self._shutdown_event.set)

    # ---- graceful drain (rolling deploys) ----
    def _drain_handler(self, ctx) -> dict:
        """POST /.well-known/debug/drain — begin the graceful drain from
        the deploy controller's preStop hook (the SIGTERM path runs the
        same sequence). Idempotent: a second call reports the drain
        already in progress.

        Loopback-only by default: unlike the other debug routes this one
        is DESTRUCTIVE (takes the instance out of rotation and closes
        it), and auth middleware is opt-in — an exposed port must not be
        a one-request denial of service. The preStop hook runs inside
        the pod, so localhost covers it; GOFR_DRAIN_REMOTE=1 opts remote
        callers in for deployments that gate the route themselves
        (shared trust model with the rollout route: handler.py
        _require_loopback)."""
        from .handler import _require_loopback

        _require_loopback(ctx, "GOFR_DRAIN_REMOTE")
        started = self.begin_drain()
        return {
            "draining": True,
            "started": started,
            "deadline_s": self.drain_deadline_s,
        }

    def begin_drain(self, deadline_s: float | None = None) -> bool:
        """Flip readiness to 503 (health_handler), close engine admission
        (submit -> EngineDraining/503), wait for in-flight work up to the
        drain deadline, then shut the servers down. Returns False if a
        drain is already running. Safe from any thread (the waiter runs
        on its own daemon thread; shutdown() is loop-threadsafe)."""
        if self._draining:
            return False
        self._draining = True
        self.container.draining = True
        deadline_s = deadline_s if deadline_s is not None else self.drain_deadline_s
        self.logger.info(
            f"drain: readiness down, admission closed; finishing in-flight "
            f"work (deadline {deadline_s:.0f}s)"
        )
        rt = self.container.tpu_runtime  # never CONSTRUCT the runtime here
        if rt is not None:
            try:
                rt.drain()
            except Exception as e:  # noqa: BLE001 — drain must reach shutdown
                self.logger.error(f"drain: engine drain failed: {e!r}")
        fr = getattr(self.container, "front_router", None)
        if fr is not None:
            try:
                # stop the autoscaler but LEAVE managed engines serving:
                # a rolling router deploy must not take the fleet's
                # capacity down with it (container.close() reaps on a
                # real process exit)
                fr.drain()
            except Exception as e:  # noqa: BLE001 — drain must reach shutdown
                self.logger.error(f"drain: front-router drain failed: {e!r}")
        threading.Thread(
            target=self._drain_then_stop, args=(deadline_s,),
            name="app-drain", daemon=True,
        ).start()
        return True

    def _drain_then_stop(self, deadline_s: float) -> None:
        import time as _time

        # grace floor even when nothing is in flight: the load balancer
        # must get at least one readiness probe window at 503 (and the
        # drain POST its response) before the listener closes
        _time.sleep(min(0.5, deadline_s))
        t0 = _time.monotonic()
        while _time.monotonic() - t0 < deadline_s:
            rt = self.container.tpu_runtime
            try:
                if rt is None or rt.drained():
                    break
            except Exception:  # noqa: BLE001 — a sick engine must not wedge exit
                break
            _time.sleep(0.05)
        else:
            self.logger.warn(
                f"drain: deadline {deadline_s:.0f}s elapsed with work still "
                "in flight; shutting down anyway"
            )
        self.shutdown()

    def run(self) -> None:
        """Blocking entrypoint with signal-driven graceful shutdown.

        HTTP_WORKERS=N (N>1) enables prefork multi-worker serving for
        CPU-bound apps: N processes share HTTP and metrics ports via
        SO_REUSEPORT (kernel-balanced accepts), sidestepping the GIL the
        way the reference relies on Go's runtime threads (httpServer.go:26
        league). The fork happens before any server starts; a scrape of
        /metrics samples one worker. Not compatible with an initialized
        JAX runtime (device handles don't survive fork) — TPU apps scale
        by engine replicas (ReplicatedLLMEngine) instead, so if JAX is
        already imported the app logs a warning and serves single-process.
        """
        workers = self.config.get_int("HTTP_WORKERS", 1)
        child_pids: list[int] = []
        if workers > 1:
            child_pids = self._fork_workers(workers)

        async def main():
            loop = asyncio.get_running_loop()
            # SIGTERM = the orchestrator's rolling-deploy signal: drain
            # gracefully (readiness 503, finish in-flight, then close).
            # SIGINT = a human at the keyboard: stop now.
            for sig, handler in (
                (signal.SIGINT, self.shutdown),
                (signal.SIGTERM, self.begin_drain),
            ):
                try:
                    loop.add_signal_handler(sig, handler)
                except (NotImplementedError, RuntimeError):
                    pass
            await self.serve()

        try:
            asyncio.run(main())
        finally:
            if child_pids:
                self._reap_workers(child_pids)

    @staticmethod
    def _reap_workers(pids: list[int], grace: float = 10.0) -> None:
        """SIGTERM each worker, wait up to `grace` seconds, SIGKILL any
        survivor — a worker wedged in a C call must not hang the parent's
        exit forever."""
        import os
        import time as _time

        for pid in pids:
            try:
                os.kill(pid, signal.SIGTERM)
            except ProcessLookupError:
                pass
        deadline = _time.monotonic() + grace
        remaining = list(pids)
        while remaining and _time.monotonic() < deadline:
            for pid in list(remaining):
                try:
                    done, _ = os.waitpid(pid, os.WNOHANG)
                except ChildProcessError:
                    done = pid
                if done:
                    remaining.remove(pid)
            if remaining:
                _time.sleep(0.05)
        for pid in remaining:
            try:
                os.kill(pid, signal.SIGKILL)
                os.waitpid(pid, 0)
            except (ProcessLookupError, ChildProcessError):
                pass

    def _fork_workers(self, workers: int) -> list[int]:
        """Fork workers-1 children sharing the ports via SO_REUSEPORT.
        Returns child pids in the parent, [] in a child (or when multi-
        worker is unavailable on this platform/runtime)."""
        import os
        import socket
        import sys

        if not hasattr(socket, "SO_REUSEPORT"):
            self.logger.warn("HTTP_WORKERS: SO_REUSEPORT unsupported; single worker")
            return []
        if "jax" in sys.modules:
            self.logger.warn(
                "HTTP_WORKERS>1 ignored: JAX already imported and device "
                "state does not survive fork — use engine replicas to scale"
            )
            return []
        if self.http_port == 0 or self.metrics_port == 0:
            # reuse_port on port 0 would give every worker its OWN random
            # port — three of four workers would serve unreachable sockets
            self.logger.warn(
                "HTTP_WORKERS>1 ignored: ephemeral port 0 cannot be shared "
                "across workers; set fixed HTTP_PORT/METRICS_PORT"
            )
            return []
        if self.config.get("REMOTE_LOG_URL"):
            # threads do not survive fork: only the parent's poller lives on
            self.logger.warn(
                "HTTP_WORKERS>1: remote log-level polling runs in the "
                "parent worker only"
            )
        # NOTE: datasource connections opened BEFORE run() (user startup
        # code) would share one socket fd across workers and interleave
        # protocol frames — framework datasources connect lazily/reconnect
        # per process, but user-held sockets are the caller's contract.
        self.http_server.reuse_port = True
        self.metrics_server.reuse_port = True
        pids: list[int] = []
        try:
            for _ in range(workers - 1):
                pid = os.fork()
                if pid == 0:
                    return []  # child: serve like a normal process
                pids.append(pid)
        except OSError:
            # partial fork failure: never orphan the workers already alive
            self._reap_workers(pids)
            raise
        self.logger.info(f"HTTP multi-worker: {workers} processes on :{self.http_port}")
        return pids

    # -- test helper: run the app in a daemon thread, return when ready --
    def run_in_background(self) -> threading.Thread:
        started = threading.Event()

        async def main():
            task = asyncio.ensure_future(self.serve())
            while self.http_server._server is None and not task.done():
                await asyncio.sleep(0.005)
            started.set()
            await task

        t = threading.Thread(target=lambda: asyncio.run(main()), daemon=True)
        t.start()
        if not started.wait(timeout=30):
            raise RuntimeError("app failed to start")
        return t


def new(config: Config | None = None, configs_dir: str = "./configs") -> App:
    """gofr.New() analogue (gofr.go:64)."""
    return App(config=config, configs_dir=configs_dir)
