"""Incident flight recorder: per-request black-box records, crash
bundles, deterministic replay, and rolling-baseline perf-anomaly
detection (docs/advanced-guide/incident-debugging.md).

The serving stack's live observability (phase histograms, trace
journeys, SLO burn rates) answers "how is it going"; this module
answers "what just happened" after the process is compromised:

- :class:`FlightRecorder` — a bounded per-engine ring of flight
  records (``TPU_LLM_FLIGHT_RECORDS``, default 512): everything needed
  to re-execute one request bit-for-bit — prompt token ids (or only a
  hash under ``TPU_LLM_FLIGHT_REDACT``), sampling params + seed, model
  name/version, adapter, grammar id, KV layout and spec/constrained
  flags, per-phase timings, deaths/hops/journey id, finish reason, and
  the emitted token ids. Records finalize on EVERY terminal path,
  including ``_die``.

- :class:`BlackboxDumper` — the aircraft black box: on watchdog trip,
  numerical trip, poison verdict, device quarantine, rollout rollback,
  SLO fast-burn flip, or a flagged perf anomaly, dump a bundle
  directory under ``GOFR_BLACKBOX_DIR`` (rate-limited per trigger
  class) holding debug_state, the trace ring, the last wide events,
  the compile registry, HBM samples, a config fingerprint, and the
  flight records of everything in flight. ``app_blackbox_bundles_total
  {trigger}`` counts dumps; the router fans ``GET
  /.well-known/debug/blackbox`` over the fleet.

- :func:`replay_record` — deterministic replay: re-submit a recorded
  request with pinned version/adapter/grammar/seed and report the
  first-divergence token index vs the recorded emission. Greedy replay
  is token-identical across every engine layout (test-pinned).

- :class:`AnomalyDetector` — rolling-baseline detectors over
  TTFT/TPOT/step wall/queue wait/spec acceptance
  (``metrics.RollingWindow`` underneath): a sustained deviation flags
  ``app_llm_anomaly{signal}`` and triggers a perf-incident bundle, so
  slow-is-broken gets the same evidence as crashed.

All of it is passive until armed: with ``GOFR_BLACKBOX_DIR`` unset no
bundle is ever written, and the recorder's steady-state cost is one
dict write per request terminal.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Callable

__all__ = [
    "FLIGHT_RECORDS_DEFAULT",
    "WIDE_EVENTS_KEEP",
    "AnomalyDetector",
    "BlackboxDumper",
    "FlightRecorder",
    "classify_die_reason",
    "find_record",
    "first_divergence",
    "register_flightrec_metrics",
    "replay_record",
]

FLIGHT_RECORDS_DEFAULT = 512
# last-N wide events retained for bundles (the log line deque the
# sampling satellite may have skipped emitting still lands here in full)
WIDE_EVENTS_KEEP = 256
# newest manifests a listing returns (bounded: the endpoint must be safe
# against a directory that accumulated months of incidents)
LISTING_LIMIT = 64

_REG_LOCK = threading.Lock()


def register_flightrec_metrics(metrics) -> None:
    """Idempotent registration (register_slo_metrics' pattern)."""
    with _REG_LOCK:
        if not metrics.has("app_blackbox_bundles_total"):
            metrics.new_counter(
                "app_blackbox_bundles_total",
                "black-box incident bundles written (trigger labels the "
                "incident class: watchdog|numerical|poison|engine_death|"
                "quarantine|rollback|slo_fast_burn|anomaly|manual)",
            )
        if not metrics.has("app_llm_anomaly"):
            metrics.new_gauge(
                "app_llm_anomaly",
                "1 while the labelled signal (ttft|tpot|step|queue_wait|"
                "spec_accept) is sustained-deviant from its rolling "
                "baseline (zeroed at engine close)",
            )


def _sha256_tokens(tokens) -> str:
    h = hashlib.sha256()
    for t in tokens:
        h.update(int(t).to_bytes(8, "little", signed=True))
    return h.hexdigest()


def classify_die_reason(why: str) -> str:
    """Map an engine death reason onto its bundle trigger class."""
    why = why or ""
    if why.startswith("step watchdog"):
        return "watchdog"
    if why.startswith("numerical watchdog"):
        return "numerical"
    if why.startswith("poison payload"):
        return "poison"
    return "engine_death"


class FlightRecorder:
    """Bounded ring of per-request flight records, keyed by request id.

    ``start()`` captures the re-execution inputs at submit time (so an
    in-flight request is already replayable when the engine dies);
    ``finalize()`` stamps the terminal outcome — timings, finish
    reason, emitted token ids. The ring holds ``capacity`` records and
    evicts oldest-first; capacity 0 disables recording entirely.

    Redaction (``TPU_LLM_FLIGHT_REDACT=1`` or ``redact=True``) keeps
    only sha256 hashes of the prompt and emission — the record still
    proves WHAT ran and whether a replay diverged elsewhere, without
    persisting tenant content in process memory or bundles.

    The grammar OBJECT rides the record under the non-serializable
    ``_grammar`` key (replay re-submits it); ``serializable()`` strips
    underscore keys for bundles and HTTP responses."""

    def __init__(
        self,
        capacity: int | None = None,
        *,
        redact: bool | None = None,
        clock: Callable[[], float] | None = None,
    ):
        if capacity is None:
            capacity = int(
                os.environ.get("TPU_LLM_FLIGHT_RECORDS", "")
                or FLIGHT_RECORDS_DEFAULT
            )
        self.capacity = max(0, int(capacity))
        if redact is None:
            redact = os.environ.get("TPU_LLM_FLIGHT_REDACT", "0") not in ("", "0")
        self.redact = bool(redact)
        self._clock = clock if clock is not None else time.time
        self._lock = threading.Lock()
        self._ring: OrderedDict[int, dict] = OrderedDict()

    @property
    def enabled(self) -> bool:
        return self.capacity > 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def _tokens_fields(self, prefix: str, tokens) -> dict:
        toks = [int(t) for t in tokens]
        out = {
            f"{prefix}_len": len(toks),
            f"{prefix}_sha256": _sha256_tokens(toks),
        }
        out[f"{prefix}_token_ids"] = None if self.redact else toks
        return out

    def start(self, req, engine) -> None:
        """Capture the re-execution inputs at submit time. Called once
        per (re)submit — a failover continuation overwrites its own
        record with the continuation prompt, which is exactly what a
        replay of THIS engine's work needs."""
        if not self.enabled:
            return
        kv = getattr(engine, "kv", None)
        layout = "dense"
        if kv is not None and getattr(kv, "paged", False):
            layout = "paged"
        elif kv is not None and getattr(kv, "ring", None):
            layout = "windowed"
        rec = {
            "id": req.id,
            "model": engine.label,
            "model_version": engine.version,
            # every engine seeds its sampler from PRNGKey(rng_seed):
            # greedy ignores it, temperature>0 replays pin it
            "seed": int(getattr(engine, "rng_seed", 0)),
            "temperature": float(req.temperature),
            "max_new_tokens": int(req.max_new_tokens),
            "eos_token": int(req.eos_token),
            "priority": req.priority,
            "client": req.client,
            "session_id": req.session_id,
            "adapter": req.adapter or "",
            "adapter_version": (
                f"{req.adapter}@{req._aid}" if req.adapter else ""
            ),
            "grammar_id": (
                f"g{req._g_id}" if getattr(req, "_g_id", -1) >= 0 else None
            ),
            "kv_layout": layout,
            "speculative": bool(getattr(engine, "speculative", False)),
            "constrained": req.grammar is not None,
            "lora": bool(req.adapter),
            "submitted_ts": self._clock(),
            "hop": req.hop,
            "deaths": req.deaths,
            "retries": req.retries,
            "journey_id": req.journey_id or "",
            "trace_id": req.span.trace_id if req.span is not None else "",
            "finish_reason": None,
            "final": False,
            "redacted": self.redact,
            **self._tokens_fields("prompt", req.prompt_tokens),
        }
        if req.grammar is not None:
            rec["_grammar"] = req.grammar
        with self._lock:
            self._ring[req.id] = rec
            self._ring.move_to_end(req.id)
            while len(self._ring) > self.capacity:
                self._ring.popitem(last=False)

    def finalize(
        self,
        req,
        *,
        queue_wait_ms=None,
        ttft_ms=None,
        per_token_ms=None,
        total_ms=None,
        chip=None,
    ) -> dict | None:
        """Stamp the terminal outcome. Every terminal path lands here —
        the regular finish observer AND the die-drain paths — so a
        record is never left dangling non-final for a finished request."""
        if not self.enabled:
            return None
        with self._lock:
            rec = self._ring.get(req.id)
        if rec is None:
            return None
        rec.update({
            "final": True,
            "finish_reason": req.finish_reason,
            "hop": req.hop,
            "deaths": req.deaths,
            "retries": req.retries,
            "capped": req.capped,
            "browned": req.browned,
            "prefix_hit": req.prefix_hit,
            "finished_ts": self._clock(),
            "phase_ms": {
                "queue_wait": queue_wait_ms,
                "ttft": ttft_ms,
                "per_token": per_token_ms,
                "total": total_ms,
            },
            # chip-time attribution by waste class (gofr_tpu.goodput;
            # milliseconds) — the per-request cost line an incident
            # bundle carries alongside the latency breakdown
            "chip_ms": chip,
            # history holds the tokens emitted since THIS engine's
            # submit — exactly the emission a replay of the recorded
            # prompt reproduces
            **self._tokens_fields("emitted", req.history),
        })
        return rec

    def get(self, rid: int) -> dict | None:
        with self._lock:
            return self._ring.get(int(rid))

    def records(self, limit: int | None = None, final=None) -> list[dict]:
        """Newest-first record list; ``final`` filters terminal state."""
        with self._lock:
            out = list(self._ring.values())[::-1]
        if final is not None:
            out = [r for r in out if bool(r.get("final")) == final]
        if limit is not None:
            out = out[: max(0, int(limit))]
        return out

    def snapshot_inflight(self, reqs) -> list[dict]:
        """The bundle's in-flight view: every live request's record with
        its progress-so-far stamped (non-final — the death that
        triggered the bundle has not finished them). Requests the ring
        already evicted get a fresh minimal row so the bundle never
        silently omits an in-flight request."""
        out = []
        seen: set[int] = set()
        for r in reqs:
            if r is None or r.id in seen:
                continue
            seen.add(r.id)
            rec = self.get(r.id)
            if rec is None:
                rec = {"id": r.id, "evicted": True}
            rec = dict(rec)
            rec.update({
                "final": False,
                "phase": r.phase,
                "finish_reason": r.finish_reason,
                "hop": r.hop,
                "deaths": r.deaths,
                **self._tokens_fields("emitted", r.history),
            })
            out.append(rec)
        return out

    @staticmethod
    def serializable(rec: dict) -> dict:
        return {k: v for k, v in rec.items() if not k.startswith("_")}

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()


def first_divergence(recorded, replayed) -> int | None:
    """Index of the first token where the replay diverges from the
    recorded emission; None when token-identical (same tokens, same
    length). A pure-prefix mismatch diverges at the shorter length."""
    a = list(recorded or [])
    b = list(replayed or [])
    for i, (x, y) in enumerate(zip(a, b)):
        if int(x) != int(y):
            return i
    if len(a) != len(b):
        return min(len(a), len(b))
    return None


def replay_record(engine, record: dict, *, timeout: float = 120.0) -> dict:
    """Deterministic replay: re-submit ``record``'s request against
    ``engine`` with pinned version/adapter/grammar/seed and report the
    first-divergence index vs the recorded emission.

    The pinning is strict: a version mismatch is an error, not a silent
    cross-version comparison — different weights legitimately emit
    different tokens and the report would be noise. Redacted records
    cannot replay (the prompt is gone by design)."""
    rec = record
    if rec.get("redacted") or rec.get("prompt_token_ids") is None:
        return {
            "id": rec.get("id"),
            "error": "record redacted (TPU_LLM_FLIGHT_REDACT): prompt "
                     "tokens unavailable for replay",
        }
    want_version = rec.get("model_version")
    if want_version and engine.version != want_version:
        return {
            "id": rec.get("id"),
            "error": f"version mismatch: record pinned to "
                     f"{want_version!r}, engine serves {engine.version!r}",
        }
    recorded = list(rec.get("emitted_token_ids") or [])
    finish = rec.get("finish_reason")
    if finish in ("eos", "length"):
        max_new = int(rec.get("max_new_tokens") or max(1, len(recorded)))
    else:
        # cancelled/shed/failover-partial streams: replay only the prefix
        # the original actually emitted — decoding past it compares nothing
        max_new = max(1, len(recorded))
    from ..llm import GenRequest

    req = GenRequest(
        list(rec["prompt_token_ids"]),
        max_new_tokens=max_new,
        temperature=float(rec.get("temperature") or 0.0),
        eos_token=int(
            rec["eos_token"] if rec.get("eos_token") is not None else -1
        ),
        priority=rec.get("priority") or "interactive",
        client="flightrec-replay",
        grammar=rec.get("_grammar"),
        adapter=rec.get("adapter") or "",
        probe=True,  # debug traffic: goodput classes it as probe waste
    )
    t0 = time.perf_counter()
    replayed = engine.submit(req).tokens(timeout=timeout)
    div = first_divergence(recorded, replayed)
    return {
        "id": rec.get("id"),
        "model": rec.get("model"),
        "model_version": engine.version,
        "recorded_len": len(recorded),
        "replayed_len": len(replayed),
        "first_divergence": div,
        "match": div is None,
        "recorded_token_ids": recorded,
        "replayed_token_ids": replayed,
        "replay_finish_reason": req.finish_reason,
        "replay_ms": round((time.perf_counter() - t0) * 1e3, 1),
    }


def find_record(engine, rid: int) -> tuple[dict, Any] | tuple[None, None]:
    """Locate flight record ``rid`` across an engine handle — a bare
    LLMEngine, a ReplicatedLLMEngine (search replicas), or anything
    exposing ``engines``. Returns (record, owning engine)."""
    for eng in getattr(engine, "engines", None) or [engine]:
        fr = getattr(eng, "flightrec", None)
        if fr is None:
            continue
        rec = fr.get(rid)
        if rec is not None:
            return rec, eng
    return None, None


class BlackboxDumper:
    """Write incident bundles under ``GOFR_BLACKBOX_DIR``.

    One bundle is a directory ``<label>-<trigger>-<seq>/`` of small
    JSON files (manifest, debug_state, trace ring, wide events, compile
    registry, HBM samples, config fingerprint, flight records) — the
    exact evidence an engineer needs when the process that held it is
    gone. Dumps are rate-limited PER TRIGGER CLASS
    (``GOFR_BLACKBOX_INTERVAL_S``, default 60 s): a crash loop or a
    flapping anomaly produces one bundle per window, not a disk full of
    identical ones. Unconfigured (empty dir) the dumper is inert."""

    def __init__(
        self,
        directory: str | None = None,
        *,
        min_interval_s: float | None = None,
        clock: Callable[[], float] | None = None,
        logger=None,
        metrics=None,
        label: str = "llm",
    ):
        if directory is None:
            directory = os.environ.get("GOFR_BLACKBOX_DIR", "")
        self.directory = directory or ""
        if min_interval_s is None:
            min_interval_s = float(
                os.environ.get("GOFR_BLACKBOX_INTERVAL_S", "") or 60.0
            )
        self.min_interval_s = max(0.0, float(min_interval_s))
        self._clock = clock if clock is not None else time.time
        self.logger = logger
        self.metrics = metrics
        self.label = label
        self._lock = threading.Lock()
        self._last: dict[str, float] = {}  # trigger class -> last dump ts
        self._seq = 0
        self._closed = False
        self.last_ts: float | None = None  # newest dump (serving summary)
        self.last_trigger: str | None = None
        self.rate_limited = 0
        self._manifests: deque = deque(maxlen=LISTING_LIMIT)
        if metrics is not None:
            register_flightrec_metrics(metrics)

    def enabled(self) -> bool:
        return bool(self.directory) and not self._closed

    def close(self) -> None:
        """close()/_die() contract (the dead-engine-gauge rule's file
        sibling): a torn-down engine must not write further bundles."""
        self._closed = True

    def dump(
        self,
        trigger: str,
        *,
        reason: str = "",
        sections: dict[str, Any] | None = None,
        records: list[dict] | None = None,
    ) -> str | None:
        """Write one bundle; returns its path, or None when disabled or
        rate-limited. Never raises — the incident path must survive a
        full disk or an unwritable directory."""
        if not self.enabled():
            return None
        now = self._clock()
        with self._lock:
            last = self._last.get(trigger)
            if (
                last is not None
                and self.min_interval_s > 0
                and now - last < self.min_interval_s
            ):
                self.rate_limited += 1
                return None
            self._last[trigger] = now
            self._seq += 1
            seq = self._seq
        name = f"{self.label.replace('/', '_')}-{trigger}-{seq:04d}"
        path = os.path.join(self.directory, name)
        manifest = {
            "bundle": name,
            "label": self.label,
            "trigger": trigger,
            "reason": reason,
            "ts": now,
            "sections": sorted(sections or {}),
            "flight_records": len(records or []),
        }
        try:
            os.makedirs(path, exist_ok=True)
            for fname, payload in (sections or {}).items():
                self._write_json(os.path.join(path, f"{fname}.json"), payload)
            if records is not None:
                self._write_json(
                    os.path.join(path, "flight_records.json"),
                    [FlightRecorder.serializable(r) for r in records],
                )
            # manifest LAST: its presence marks the bundle complete, so
            # a listing never serves a half-written directory as done
            self._write_json(os.path.join(path, "manifest.json"), manifest)
        except OSError as e:
            if self.logger is not None:
                self.logger.error(f"blackbox bundle write failed: {e!r}")
            return None
        self.last_ts = now
        self.last_trigger = trigger
        with self._lock:
            self._manifests.append(manifest)
        if self.metrics is not None:
            self.metrics.increment_counter(
                "app_blackbox_bundles_total",
                trigger=trigger, model=self.label,
            )
        if self.logger is not None:
            self.logger.error(
                f"black-box bundle written: {path} (trigger={trigger})"
            )
        return path

    @staticmethod
    def _write_json(path: str, payload: Any) -> None:
        with open(path, "w") as f:
            json.dump(payload, f, indent=1, default=repr)

    def listing(self) -> list[dict]:
        """Manifests of completed bundles in the directory (newest
        first, bounded) — includes bundles other processes sharing the
        dir wrote, which is what a fleet-wide listing wants."""
        if not self.directory or not os.path.isdir(self.directory):
            with self._lock:
                return list(self._manifests)[::-1]
        out = []
        try:
            names = os.listdir(self.directory)
        except OSError:
            names = []
        for name in names:
            mpath = os.path.join(self.directory, name, "manifest.json")
            try:
                with open(mpath) as f:
                    m = json.load(f)
            except (OSError, ValueError):
                continue
            m["path"] = os.path.join(self.directory, name)
            out.append(m)
        out.sort(key=lambda m: m.get("ts") or 0, reverse=True)
        return out[:LISTING_LIMIT]


# signal name -> deviation direction: +1 flags values ABOVE the
# baseline (latencies), -1 flags values BELOW it (acceptance rates)
ANOMALY_SIGNALS = {
    "ttft": 1,
    "tpot": 1,
    "step": 1,
    "queue_wait": 1,
    "spec_accept": -1,
}


class AnomalyDetector:
    """Sustained-deviation detection against rolling baselines.

    Per signal, a long :class:`~gofr_tpu.metrics.RollingWindow` holds
    the NORMAL regime (only non-deviant observations feed it — an
    anomaly must not become its own baseline; after ``max_age_s`` with
    nothing but deviant traffic the baseline ages out and the detector
    recalibrates to the new normal). An observation is deviant when it
    exceeds ``factor`` x the baseline mean (or falls below mean/factor
    for lower-is-worse signals); ``sustain`` consecutive deviants flag
    the signal — one p99 straggler never pages — and ``sustain``
    consecutive normals clear it. Flag transitions publish
    ``app_llm_anomaly{model,signal}`` and fire ``on_flag`` (the
    perf-incident bundle trigger)."""

    def __init__(
        self,
        metrics=None,
        label: str = "llm",
        *,
        factor: float | None = None,
        min_samples: int | None = None,
        sustain: int | None = None,
        max_age_s: float = 3600.0,
        clock: Callable[[], float] | None = None,
        on_flag: Callable[[str, float, float], None] | None = None,
    ):
        from ..metrics import RollingWindow

        if factor is None:
            factor = float(os.environ.get("TPU_LLM_ANOMALY_FACTOR", "") or 3.0)
        if min_samples is None:
            min_samples = int(
                os.environ.get("TPU_LLM_ANOMALY_MIN_SAMPLES", "") or 64
            )
        if sustain is None:
            sustain = int(os.environ.get("TPU_LLM_ANOMALY_SUSTAIN", "") or 8)
        self.factor = max(1.0, float(factor))
        self.min_samples = max(1, int(min_samples))
        self.sustain = max(1, int(sustain))
        self.metrics = metrics
        self.label = label
        self.on_flag = on_flag
        self._lock = threading.Lock()
        self._baseline = {
            s: RollingWindow(size=2048, max_age_s=max_age_s, clock=clock)
            for s in ANOMALY_SIGNALS
        }
        self._streak = dict.fromkeys(ANOMALY_SIGNALS, 0)  # consecutive deviants
        self._normal = dict.fromkeys(ANOMALY_SIGNALS, 0)  # consecutive normals
        self._flagged: set[str] = set()
        self._last: dict[str, float] = {}
        if metrics is not None:
            register_flightrec_metrics(metrics)

    def observe(self, signal: str, value: float) -> bool:
        """Feed one observation; returns whether the signal is flagged
        after it. Unknown signals are ignored (forward compat)."""
        direction = ANOMALY_SIGNALS.get(signal)
        if direction is None:
            return False
        value = float(value)
        fired = None
        with self._lock:
            base = self._baseline[signal]
            self._last[signal] = value
            deviant = False
            mean = 0.0
            if len(base) >= self.min_samples:
                mean = base.mean()
                if direction > 0:
                    deviant = value > self.factor * mean
                else:
                    deviant = value < mean / self.factor
            if deviant:
                self._streak[signal] += 1
                self._normal[signal] = 0
                if (
                    signal not in self._flagged
                    and self._streak[signal] >= self.sustain
                ):
                    self._flagged.add(signal)
                    fired = (value, mean)
                    self._publish(signal, 1.0)
            else:
                base.observe(value)  # only normal traffic is baseline
                self._streak[signal] = 0
                self._normal[signal] += 1
                if (
                    signal in self._flagged
                    and self._normal[signal] >= self.sustain
                ):
                    self._flagged.discard(signal)
                    self._publish(signal, 0.0)
            flagged = signal in self._flagged
        if fired is not None and self.on_flag is not None:
            try:
                self.on_flag(signal, fired[0], fired[1])
            except Exception:  # noqa: BLE001 — detection must not break serving
                pass
        return flagged

    def _publish(self, signal: str, value: float) -> None:
        if self.metrics is not None:
            self.metrics.set_gauge(
                "app_llm_anomaly", value, model=self.label, signal=signal
            )

    def flagged(self) -> list[str]:
        with self._lock:
            return sorted(self._flagged)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                s: {
                    "flagged": s in self._flagged,
                    "streak": self._streak[s],
                    "baseline_mean": (
                        self._baseline[s].mean() if len(self._baseline[s]) else None
                    ),
                    "baseline_samples": len(self._baseline[s]),
                    "last": self._last.get(s),
                }
                for s in ANOMALY_SIGNALS
            }

    def zero_gauges(self) -> None:
        """close()/_die(): a dead engine must not hold an anomaly flag
        (the dead-engine-gauge regression class), and a restarted one
        starts against a fresh baseline."""
        with self._lock:
            self._flagged.clear()
            for s in ANOMALY_SIGNALS:
                self._streak[s] = 0
                self._normal[s] = 0
                self._baseline[s].clear()
        if self.metrics is not None:
            for s in ANOMALY_SIGNALS:
                self._publish(s, 0.0)
