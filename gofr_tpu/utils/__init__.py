"""gofr_tpu.utils — small shared helpers."""

from __future__ import annotations

import re

_SNAKE_RE = re.compile(r"(?<!^)(?=[A-Z])")


def snake_case(name: str) -> str:
    """CamelCase -> snake_case; shared by ORM column mapping (datasource.sql)
    and CRUD table/path derivation (crud) so the two never diverge."""
    return _SNAKE_RE.sub("_", name).lower()


_CACHE_ENABLED = False


def enable_compilation_cache(directory: str | None = None, logger=None) -> None:
    """Turn on JAX's persistent (on-disk) compilation cache, idempotently.

    Serving-engine cold starts are dominated by XLA compiles (Gemma-2B
    engine: ~14 s of prefill/decode-chunk programs). The disk cache makes
    every init after the first take seconds — a server restart should not
    pay the compiler again. Directory: GOFR_XLA_CACHE_DIR or
    ~/.cache/gofr_tpu/xla. Failures degrade to cold compiles, never crash.
    """
    global _CACHE_ENABLED
    if _CACHE_ENABLED:
        return
    import os

    directory = (
        directory
        or os.environ.get("GOFR_XLA_CACHE_DIR")
        or os.path.join(os.path.expanduser("~"), ".cache", "gofr_tpu", "xla")
    )
    try:
        import jax

        if getattr(jax.config, "jax_compilation_cache_dir", None):
            # the application configured its own cache dir — respect it
            _CACHE_ENABLED = True
            return
        os.makedirs(directory, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", directory)
        # default min sizes skip small programs; serving wants them ALL
        # (the admission scatters compile fast but still cost a cold start)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
        # jax initializes its cache object on the FIRST compile and never
        # re-reads the config: if anything compiled before this call (any
        # jax work ahead of engine init), the dir update alone is a silent
        # no-op and every compile stays uncached. Reset so the next
        # compile rebuilds the cache against the configured dir.
        try:
            from jax._src.compilation_cache import reset_cache

            reset_cache()
        except Exception:  # noqa: BLE001 — older jax: first-compile init
            pass
        _CACHE_ENABLED = True
        if logger is not None:
            logger.debug(f"XLA persistent compilation cache at {directory}")
    except Exception as e:  # noqa: BLE001 — cache is an optimization only
        if logger is not None:
            logger.warn(f"compilation cache disabled: {e}")


def pin_jax_platform(platform: str, logger=None) -> bool:
    """Pin the jax backend (jax.config jax_platforms) and VERIFY it took.

    jax.config.update silently no-ops once a backend is initialized, so the
    only reliable failure signal is comparing jax.default_backend() after
    the update. Returns True when the requested platform is active.
    """
    if not platform:
        return True
    import jax

    try:
        jax.config.update("jax_platforms", platform)
    except Exception as e:  # noqa: BLE001 — defensive; update may raise pre-0.9
        if logger is not None:
            logger.warn(f"TPU_PLATFORM={platform} not applied: {e}")
        return False
    active = jax.default_backend()
    # jax_platforms may list fallbacks ("tpu,cpu"); accept any listed entry.
    wanted = [p.strip() for p in platform.split(",") if p.strip()]
    if active not in wanted and not (active == "tpu" and "axon" in wanted):
        if logger is not None:
            logger.warn(
                f"TPU_PLATFORM={platform} ignored: jax already initialized "
                f"on '{active}' (set it before any jax usage)"
            )
        return False
    return True
