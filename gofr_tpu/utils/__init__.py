"""gofr_tpu.utils — small shared helpers."""

from __future__ import annotations

import re

_SNAKE_RE = re.compile(r"(?<!^)(?=[A-Z])")


def snake_case(name: str) -> str:
    """CamelCase -> snake_case; shared by ORM column mapping (datasource.sql)
    and CRUD table/path derivation (crud) so the two never diverge."""
    return _SNAKE_RE.sub("_", name).lower()


def pin_jax_platform(platform: str, logger=None) -> bool:
    """Pin the jax backend (jax.config jax_platforms) and VERIFY it took.

    jax.config.update silently no-ops once a backend is initialized, so the
    only reliable failure signal is comparing jax.default_backend() after
    the update. Returns True when the requested platform is active.
    """
    if not platform:
        return True
    import jax

    try:
        jax.config.update("jax_platforms", platform)
    except Exception as e:  # noqa: BLE001 — defensive; update may raise pre-0.9
        if logger is not None:
            logger.warn(f"TPU_PLATFORM={platform} not applied: {e}")
        return False
    active = jax.default_backend()
    # jax_platforms may list fallbacks ("tpu,cpu"); accept any listed entry.
    wanted = [p.strip() for p in platform.split(",") if p.strip()]
    if active not in wanted and not (active == "tpu" and "axon" in wanted):
        if logger is not None:
            logger.warn(
                f"TPU_PLATFORM={platform} ignored: jax already initialized "
                f"on '{active}' (set it before any jax usage)"
            )
        return False
    return True
