"""Context: the per-request facade handed to every handler.

Parity: reference pkg/gofr/context.go:12-71 — embeds the request, the
container, and a trace hook; the same Context shape serves HTTP, gRPC, CLI
and pub/sub handlers (context.go:23-26 states this design goal; we extend it
to gRPC, fixing the reference's asymmetry noted in SURVEY.md §3.6).
"""

from __future__ import annotations

from typing import Any

from .container import Container


class Context:
    __slots__ = ("request", "container", "_responder", "_span", "deadline")

    def __init__(self, request: Any, container: Container, responder: Any = None):
        self.request = request
        self.container = container
        self._responder = responder
        # request span (set by tracer middleware) used as trace parent when
        # the contextvar didn't propagate (e.g. executor threads)
        self._span = getattr(request, "context", {}).get("span") if request is not None else None
        self.deadline: float | None = None

    # -- request surface (delegation, context.go:53) --
    def param(self, key: str) -> str:
        return self.request.param(key)

    def params(self, key: str) -> list[str]:
        return self.request.params(key)

    def path_param(self, key: str) -> str:
        return self.request.path_param(key)

    def bind(self, target: Any = None) -> Any:
        return self.request.bind(target)

    def header(self, key: str) -> str:
        return self.request.header(key)

    def host_name(self) -> str:
        return self.request.host_name()

    # -- container surface --
    @property
    def logger(self):
        return self.container.logger

    @property
    def redis(self):
        return self.container.redis

    @property
    def sql(self):
        return self.container.sql

    @property
    def mongo(self):
        return self.container.mongo

    @property
    def metrics(self):
        return self.container.metrics

    def tpu(self):
        """The TPU datasource: model registry + batched inference.
        The build's ctx.TPU() requirement (BASELINE.json north_star)."""
        return self.container.tpu()

    def get_http_service(self, name: str):
        return self.container.get_http_service(name)

    def get_publisher(self):
        return self.container.get_publisher()

    # -- tracing (context.go:45-51) --
    def trace(self, name: str):
        from .tracing import current_span

        parent = current_span()
        tracer = getattr(self.container, "tracer", None)
        if tracer is None:
            from .tracing import Tracer

            tracer = Tracer(self.container.app_name)
            self.container.tracer = tracer  # type: ignore[attr-defined]
        span = tracer.start_span(name)
        if parent is None and self._span is not None:
            span.trace_id = self._span.trace_id
            span.parent_id = self._span.span_id
        return span

    @property
    def trace_id(self) -> str:
        span = self.request.context.get("span") if hasattr(self.request, "context") else None
        return span.trace_id if span else ""

    @property
    def traceparent(self) -> str:
        """W3C traceparent of the active span (contextvar first, request
        span as fallback). Attach it to work that crosses into threads the
        contextvar does not reach — e.g. GenRequest(traceparent=...) when
        submitting to the LLM engine from a custom thread — so the engine's
        phase spans land in this request's trace."""
        from .tracing import current_span

        span = current_span()
        if span is not None and span.end_ns == 0:
            return span.traceparent
        return self._span.traceparent if self._span is not None else ""

    # auth context populated by middleware
    @property
    def jwt_claims(self) -> dict | None:
        if hasattr(self.request, "context"):
            return self.request.context.get("JWTClaims")
        return None

    @property
    def authenticated_user(self) -> str | None:
        if hasattr(self.request, "context"):
            return self.request.context.get("user")
        return None


def new_context(request: Any, container: Container, responder: Any = None) -> Context:
    return Context(request, container, responder)
