"""OpenAPI serving.

Parity: reference pkg/gofr/swagger.go:13-54 + gofr.go:141-145 — when
./static/openapi.json exists, register /.well-known/openapi.json and a
/.well-known/swagger UI. The reference embeds swagger-ui's JS bundle; we
ship a dependency-free single-page renderer instead (no embedded third-party
assets), which lists paths/operations and pretty-prints the spec.
"""

from __future__ import annotations

import os

from .http.request import Request
from .http.responder import Response

_UI_HTML = """<!DOCTYPE html>
<html><head><title>API Docs</title><style>
body{font-family:system-ui,sans-serif;margin:2rem;max-width:60rem}
.op{border:1px solid #ddd;border-radius:6px;margin:.5rem 0;padding:.6rem 1rem}
.m{display:inline-block;min-width:4.5rem;font-weight:700}
.GET{color:#0b7285}.POST{color:#2b8a3e}.PUT{color:#e67700}.DELETE{color:#c92a2a}.PATCH{color:#862e9c}
pre{background:#f8f9fa;padding:1rem;border-radius:6px;overflow:auto}
summary{cursor:pointer}
</style></head><body>
<h1 id="title">API</h1><div id="ops"></div>
<details><summary>Raw spec</summary><pre id="raw"></pre></details>
<script>
fetch('/.well-known/openapi.json').then(r=>r.json()).then(spec=>{
  document.getElementById('title').textContent=(spec.info&&spec.info.title)||'API';
  document.getElementById('raw').textContent=JSON.stringify(spec,null,2);
  const ops=document.getElementById('ops');
  for(const [path,item] of Object.entries(spec.paths||{})){
    for(const [method,op] of Object.entries(item)){
      const d=document.createElement('div');d.className='op';
      const M=method.toUpperCase();
      d.innerHTML=`<span class="m ${M}">${M}</span><code>${path}</code> — ${(op&&op.summary)||''}`;
      ops.appendChild(d);
    }
  }
});
</script></body></html>""".encode("utf-8")


def register_swagger_routes(app, static_dir: str = "./static") -> None:
    spec_path = os.path.join(static_dir, "openapi.json")
    if not os.path.isfile(spec_path):
        return

    async def openapi_handler(_req: Request) -> Response:
        with open(spec_path, "rb") as f:
            body = f.read()
        return Response(200, [("Content-Type", "application/json")], body)

    async def ui_handler(_req: Request) -> Response:
        return Response(200, [("Content-Type", "text/html; charset=utf-8")], _UI_HTML)

    app.router.add("GET", "/.well-known/openapi.json", openapi_handler)
    app.router.add("GET", "/.well-known/swagger", ui_handler)
