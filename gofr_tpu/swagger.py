"""OpenAPI serving.

Parity: reference pkg/gofr/swagger.go:13-54 + gofr.go:141-145 — when
./static/openapi.json exists, register /.well-known/openapi.json and a
/.well-known/swagger UI. The reference embeds swagger-ui's JS bundle; we
ship a dependency-free single-page renderer with the same core behaviors
(operation list grouped by tag, expandable parameter/request-body/response
detail, and interactive try-it-out execution against the live server)
implemented in ~150 lines of vanilla JS — no third-party assets embedded.
"""

from __future__ import annotations

import os

from .http.request import Request
from .http.responder import Response

_UI_HTML = """<!DOCTYPE html>
<html><head><title>API Docs</title><meta charset="utf-8"><style>
body{font-family:system-ui,sans-serif;margin:2rem auto;max-width:62rem;padding:0 1rem;color:#212529}
h1{margin-bottom:.2rem} .desc{color:#495057;margin:0 0 1.2rem}
h2{font-size:1.05rem;border-bottom:1px solid #dee2e6;padding-bottom:.25rem;margin-top:1.6rem}
details.op{border:1px solid #dee2e6;border-radius:6px;margin:.5rem 0;background:#fff}
details.op>summary{cursor:pointer;padding:.55rem .9rem;list-style:none;display:flex;gap:.8rem;align-items:baseline}
details.op>summary::-webkit-details-marker{display:none}
.body{padding:.4rem .9rem .9rem;border-top:1px solid #f1f3f5}
.m{display:inline-block;min-width:4.2rem;font-weight:700;font-size:.85rem}
.GET{color:#0b7285}.POST{color:#2b8a3e}.PUT{color:#e67700}.DELETE{color:#c92a2a}.PATCH{color:#862e9c}.OPTIONS,.HEAD{color:#495057}
code{background:#f8f9fa;padding:.1rem .3rem;border-radius:4px}
.sum{color:#495057;font-size:.9rem}
table{border-collapse:collapse;width:100%;margin:.4rem 0;font-size:.9rem}
td,th{border:1px solid #e9ecef;padding:.3rem .5rem;text-align:left}
th{background:#f8f9fa}
pre{background:#f8f9fa;padding:.7rem;border-radius:6px;overflow:auto;font-size:.85rem}
textarea{width:100%;min-height:6rem;font-family:monospace;font-size:.85rem}
input[type=text]{font-family:monospace;width:100%;box-sizing:border-box}
button{background:#1971c2;color:#fff;border:0;border-radius:4px;padding:.45rem 1rem;cursor:pointer;margin:.4rem 0}
button:hover{background:#1864ab}
.resp{margin-top:.5rem}.status-ok{color:#2b8a3e;font-weight:700}.status-err{color:#c92a2a;font-weight:700}
summary.sub{cursor:pointer;font-weight:600;margin:.5rem 0 .2rem}
</style></head><body>
<h1 id="title">API</h1><p class="desc" id="descr"></p><div id="ops"></div>
<details><summary class="sub">Raw spec</summary><pre id="raw"></pre></details>
<script>
const esc=x=>String(x??'').replace(/[&<>"]/g,c=>({'&':'&amp;','<':'&lt;','>':'&gt;','"':'&quot;'}[c]));
function schemaText(s, depth){
  if(!s) return 'any';
  if(s.$ref){ return s.$ref.split('/').pop(); }
  if(s.type==='array') return schemaText(s.items, depth)+'[]';
  if(s.type==='object'||s.properties){
    if(depth>3) return 'object';
    const props=Object.entries(s.properties||{}).map(
      ([k,v])=>`  ${'  '.repeat(depth)}${k}: ${schemaText(v,(depth||0)+1)}`);
    return props.length? '{\\n'+props.join(',\\n')+'\\n'+'  '.repeat(depth||0)+'}' : 'object';
  }
  return s.type||'any';
}
function sampleFor(s, defs, depth){
  depth=depth||0;
  if(!s||depth>6) return null;  // recursive $ref schemas must terminate
  if(s.$ref){ const n=s.$ref.split('/').pop(); return sampleFor(defs[n]||{},defs,depth+1); }
  if(s.example!==undefined) return s.example;
  if(s.type==='array') return [sampleFor(s.items,defs,depth+1)];
  if(s.type==='object'||s.properties){
    const o={}; for(const [k,v] of Object.entries(s.properties||{})) o[k]=sampleFor(v,defs,depth+1);
    return o;
  }
  return {string:'',integer:0,number:0,boolean:false}[s.type] ?? null;
}
function render(spec){
  document.getElementById('title').textContent=(spec.info&&spec.info.title)||'API';
  document.getElementById('descr').textContent=(spec.info&&spec.info.description)||'';
  document.getElementById('raw').textContent=JSON.stringify(spec,null,2);
  const defs=(spec.components&&spec.components.schemas)||(spec.definitions)||{};
  const groups={};
  for(const [path,item] of Object.entries(spec.paths||{})){
    for(const [method,op] of Object.entries(item)){
      if(!/^(get|post|put|patch|delete|options|head)$/.test(method)) continue;
      const tag=(op.tags&&op.tags[0])||'default';
      (groups[tag]=groups[tag]||[]).push([path,method,op||{}]);
    }
  }
  const root=document.getElementById('ops');
  for(const [tag,entries] of Object.entries(groups)){
    if(Object.keys(groups).length>1||tag!=='default'){
      const h=document.createElement('h2'); h.textContent=tag; root.appendChild(h);
    }
    for(const [path,method,op] of entries) root.appendChild(renderOp(path,method,op,defs));
  }
}
function renderOp(path,method,op,defs){
  const M=method.toUpperCase();
  const d=document.createElement('details'); d.className='op';
  const params=(op.parameters||[]);
  const reqBody=op.requestBody&&op.requestBody.content&&
    (op.requestBody.content['application/json']||Object.values(op.requestBody.content)[0]);
  let html=`<summary><span class="m ${M}">${M}</span><code>${esc(path)}</code>`+
    `<span class="sum">${esc(op.summary||'')}</span></summary><div class="body">`;
  if(op.description) html+=`<p>${esc(op.description)}</p>`;
  if(params.length){
    html+='<table><tr><th>Parameter</th><th>In</th><th>Type</th><th>Required</th><th>Value</th></tr>';
    params.forEach((p,i)=>{
      html+=`<tr><td>${esc(p.name)}</td><td>${esc(p.in)}</td><td>${esc((p.schema&&p.schema.type)||'string')}</td>`+
        `<td>${p.required?'yes':''}</td><td><input type="text" data-p="${i}"></td></tr>`;
    });
    html+='</table>';
  }
  if(reqBody){
    html+=`<div><b>Request body</b> <code>application/json</code>`+
      `<pre>${esc(schemaText(reqBody.schema,1))}</pre>`+
      `<textarea data-body>${esc(JSON.stringify(sampleFor(reqBody.schema,defs),null,2))}</textarea></div>`;
  }
  const responses=op.responses||{};
  if(Object.keys(responses).length){
    html+='<table><tr><th>Code</th><th>Description</th></tr>';
    for(const [code,r] of Object.entries(responses))
      html+=`<tr><td>${esc(code)}</td><td>${esc((r&&r.description)||'')}</td></tr>`;
    html+='</table>';
  }
  html+='<button data-exec>Execute</button><div class="resp"></div></div>';
  d.innerHTML=html;
  d.querySelector('[data-exec]').addEventListener('click',async()=>{
    let url=path;
    const qs=new URLSearchParams();
    params.forEach((p,i)=>{
      const v=d.querySelector(`[data-p="${i}"]`).value;
      if(p.in==='path') url=url.replace('{'+p.name+'}',encodeURIComponent(v));
      else if(p.in==='query'&&v) qs.set(p.name,v);
    });
    if([...qs].length) url+='?'+qs.toString();
    const init={method:M,headers:{}};
    const ta=d.querySelector('[data-body]');
    if(ta&&ta.value.trim()){init.body=ta.value;init.headers['Content-Type']='application/json';}
    const out=d.querySelector('.resp');
    out.innerHTML='…';
    try{
      const t0=performance.now();
      const r=await fetch(url,init);
      const text=await r.text();
      let pretty=text;
      try{pretty=JSON.stringify(JSON.parse(text),null,2);}catch(e){}
      const cls=r.ok?'status-ok':'status-err';
      out.innerHTML=`<span class="${cls}">${r.status}</span> `+
        `<code>${esc(url)}</code> (${(performance.now()-t0).toFixed(0)} ms)`+
        `<pre>${esc(pretty)}</pre>`;
    }catch(e){ out.innerHTML=`<span class="status-err">network error</span> ${e}`; }
  });
  return d;
}
fetch('/.well-known/openapi.json').then(r=>r.json()).then(render);
</script></body></html>""".encode("utf-8")


def register_swagger_routes(app, static_dir: str = "./static") -> None:
    # resolve at registration: the handler re-reads per request (live spec
    # edits show up without restart), and a later os.chdir by the app must
    # not break a path captured relative to the boot cwd
    spec_path = os.path.abspath(os.path.join(static_dir, "openapi.json"))
    if not os.path.isfile(spec_path):
        return

    async def openapi_handler(_req: Request) -> Response:
        with open(spec_path, "rb") as f:
            body = f.read()
        return Response(200, [("Content-Type", "application/json")], body)

    async def ui_handler(_req: Request) -> Response:
        return Response(200, [("Content-Type", "text/html; charset=utf-8")], _UI_HTML)

    app.router.add("GET", "/.well-known/openapi.json", openapi_handler)
    app.router.add("GET", "/.well-known/swagger", ui_handler)
