"""Data migrations.

Parity: reference pkg/gofr/migration/ — run(map, container)
(migration.go:18): validate UP defined, sort int64 version keys
(migration.go:19-26), build a datasource facade over what the container has
(migration.go:98-126), ensure the tracking table (sql.go:13-19,87), find the
last applied version (sql.go:95), then per pending version run UP inside a
transaction and record (version, method, start_time, duration) on success,
rolling back on failure (migration.go:47-78).

Tracking stores: SQL table gofr_migrations (primary), Redis hash
"gofr_migrations" when only Redis is configured — same dual-store design as
the reference (migration.go getLastMigration reads the max of both).
"""

from __future__ import annotations

import asyncio
import json
import time
from typing import Any, Callable

__all__ = ["run", "Datasource", "Migration"]

_ENSURE_SQL = (
    "CREATE TABLE IF NOT EXISTS gofr_migrations ("
    " version INTEGER NOT NULL,"
    " method TEXT NOT NULL,"
    " start_time TEXT NOT NULL,"
    " duration_ms REAL,"
    " PRIMARY KEY (version, method))"
)


class Migration:
    """A migration: {"up": fn(datasource)} or a bare callable (UP)."""

    def __init__(self, up: Callable):
        self.up = up


class Datasource:
    """What a migration function receives (migration.go datasource facade):
    .sql is a transaction handle, .redis the live client, .pubsub for topic
    creation — only the configured ones are non-None."""

    def __init__(self, sql_tx=None, redis=None, pubsub=None, logger=None):
        self.sql = sql_tx
        self.redis = redis
        self.pubsub = pubsub
        self.logger = logger

    def redis_call(self, coro):
        """Run an async redis op from sync migration code."""
        return asyncio.run(coro)


def _normalize(migrations: dict[int, Any]) -> dict[int, Migration]:
    out: dict[int, Migration] = {}
    for version, m in migrations.items():
        if isinstance(m, Migration):
            out[int(version)] = m
        elif callable(m):
            out[int(version)] = Migration(m)
        elif isinstance(m, dict) and callable(m.get("up")):
            out[int(version)] = Migration(m["up"])
        else:
            raise ValueError(f"migration {version} has no UP function")
    return out


def _last_version_sql(db) -> int:
    row = db.query_row("SELECT MAX(version) AS v FROM gofr_migrations WHERE method = 'UP'")
    return int(row["v"]) if row and row["v"] is not None else 0


def _last_version_redis(redis) -> int:
    async def get():
        data = await redis.hgetall("gofr_migrations")
        return max((int(json.loads(v)["version"]) for v in data.values()), default=0)

    try:
        return asyncio.run(get())
    except Exception:  # noqa: BLE001
        return 0


def run(migrations: dict[int, Any], container) -> None:
    """app.Migrate entrypoint (gofr.go:281, migration.go:18)."""
    logger = container.logger
    ms = _normalize(migrations)
    versions = sorted(ms)
    db = container.sql
    redis = container.redis
    if db is None and redis is None:
        raise RuntimeError(
            "migrations need a datasource (configure DB_DIALECT or REDIS_HOST)"
        )

    last = 0
    if db is not None:
        db.exec(_ENSURE_SQL)
        last = max(last, _last_version_sql(db))
    if redis is not None:
        last = max(last, _last_version_redis(redis))

    ran = 0
    for version in versions:
        if version <= last:
            continue
        t0 = time.perf_counter()
        start_iso = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
        tx = db.begin() if db is not None else None
        ds = Datasource(sql_tx=tx, redis=redis, pubsub=container.pubsub, logger=logger)
        try:
            ms[version].up(ds)
            duration_ms = round((time.perf_counter() - t0) * 1e3, 3)
            if tx is not None:
                bv = db.builder.bindvar
                tx.exec(
                    "INSERT INTO gofr_migrations (version, method, start_time, duration_ms)"
                    f" VALUES ({bv(1)}, {bv(2)}, {bv(3)}, {bv(4)})",
                    version, "UP", start_iso, duration_ms,
                )
                tx.commit()
            if redis is not None:
                async def record():
                    await redis.hset(
                        "gofr_migrations", str(version),
                        json.dumps({
                            "version": version, "method": "UP",
                            "start_time": start_iso, "duration_ms": duration_ms,
                        }),
                    )

                asyncio.run(record())
            logger.info(f"migration {version} ran successfully ({duration_ms}ms)")
            ran += 1
        except Exception as e:  # noqa: BLE001
            if tx is not None:
                tx.rollback()
            logger.error(f"migration {version} failed, rolled back: {e!r}")
            raise
    if ran == 0:
        logger.info("no new migrations to run")
