"""Distributed tracing: spans, W3C trace-context propagation, exporters.

Parity: reference tracing glue (gofr.go:288-338 TracerProvider + exporter
switch jaeger|zipkin|gofr; exporter.go:36-100 custom JSON exporter;
middleware/tracer.go:15-32 traceparent extraction; service/new.go:158
injection; context.go:45-51 user spans via ctx.trace()).

Self-contained implementation: spans are plain objects, the active span lives
in a contextvar (works across asyncio tasks), and a batch exporter thread
ships finished spans. When TRACE_EXPORTER is unset the cost per span is one
object + two clock reads — cheap enough for the serving hot path.
"""

from __future__ import annotations

import contextvars
import json
import os
import queue
import threading
import time
import urllib.request
from typing import Any

_current_span: contextvars.ContextVar["Span | None"] = contextvars.ContextVar("gofr_current_span", default=None)

_TRACEPARENT_RE_VERSION = "00"


def _rand_hex(nbytes: int) -> str:
    return os.urandom(nbytes).hex()


class Span:
    __slots__ = ("trace_id", "span_id", "parent_id", "name", "start_ns", "end_ns", "attributes", "status", "links", "_token", "_tracer")

    def __init__(self, name: str, trace_id: str, span_id: str, parent_id: str | None, tracer: "Tracer | None"):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.start_ns = time.time_ns()
        self.end_ns = 0
        self.attributes: dict[str, Any] = {}
        self.status = "OK"
        self.links: list[tuple[str, str]] | None = None
        self._token = None
        self._tracer = tracer

    def set_attribute(self, key: str, value: Any) -> None:
        self.attributes[key] = value

    def add_link(self, trace_id: str, span_id: str) -> None:
        """Causal link to another span (possibly in another trace) — the
        OTel span-link: a failover continuation links the original request
        span so a multi-hop journey reads as one object even if a seam
        ever re-roots the trace."""
        if self.links is None:
            self.links = []
        self.links.append((trace_id, span_id))

    def set_status(self, status: str) -> None:
        self.status = status

    def end(self) -> None:
        if self.end_ns:
            return
        self.end_ns = time.time_ns()
        if self._token is not None:
            _current_span.reset(self._token)
            self._token = None
        if self._tracer is not None:
            self._tracer._on_end(self)

    # context-manager sugar: `with ctx.trace("name"):`
    def __enter__(self) -> "Span":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is not None:
            self.status = "ERROR"
            self.attributes.setdefault("error", repr(exc))
        self.end()

    @property
    def traceparent(self) -> str:
        return f"{_TRACEPARENT_RE_VERSION}-{self.trace_id}-{self.span_id}-01"

    @property
    def duration_us(self) -> int:
        end = self.end_ns or time.time_ns()
        return (end - self.start_ns) // 1000


def parse_traceparent(header: str | None) -> tuple[str, str] | None:
    """-> (trace_id, parent_span_id) or None. W3C: version-traceid-spanid-flags."""
    if not header:
        return None
    parts = header.strip().split("-")
    if len(parts) != 4:
        return None
    _, trace_id, span_id, _ = parts
    if len(trace_id) != 32 or len(span_id) != 16:
        return None
    try:
        int(trace_id, 16), int(span_id, 16)
    except ValueError:
        return None
    if int(trace_id, 16) == 0:
        return None
    return trace_id.lower(), span_id.lower()


class Exporter:
    def export(self, spans: list[Span]) -> None:
        raise NotImplementedError

    def shutdown(self) -> None:
        pass


class InMemoryExporter(Exporter):
    def __init__(self):
        self.spans: list[Span] = []

    def export(self, spans: list[Span]) -> None:
        self.spans.extend(spans)


class ConsoleExporter(Exporter):
    def __init__(self, logger=None):
        self._logger = logger

    def export(self, spans: list[Span]) -> None:
        for s in spans:
            line = f"trace={s.trace_id} span={s.span_id} name={s.name} dur={s.duration_us}us"
            if self._logger:
                self._logger.debug(line)


class ZipkinExporter(Exporter):
    """POSTs Zipkin-v2 JSON spans. Parity: reference exporter.go:36-100
    (its custom 'gofr' exporter is zipkin-shaped JSON)."""

    def __init__(self, endpoint: str, service_name: str):
        self.endpoint = endpoint
        self.service_name = service_name

    def export(self, spans: list[Span]) -> None:
        payload = [
            {
                "traceId": s.trace_id,
                "id": s.span_id,
                "parentId": s.parent_id,
                "name": s.name,
                "timestamp": s.start_ns // 1000,
                "duration": s.duration_us,
                "localEndpoint": {"serviceName": self.service_name},
                "tags": {
                    **{str(k): str(v) for k, v in s.attributes.items()},
                    **(
                        {
                            f"link.{i}": f"{t}/{sp}"
                            for i, (t, sp) in enumerate(s.links)
                        }
                        if s.links
                        else {}
                    ),
                },
            }
            for s in spans
        ]
        req = urllib.request.Request(
            self.endpoint,
            data=json.dumps(payload).encode("utf-8"),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=5):  # noqa: S310
            pass


class OTLPHTTPExporter(Exporter):
    """POSTs OTLP/HTTP JSON (the protocol's documented JSON encoding) to a
    collector — Jaeger natively ingests OTLP, so this is the build's
    TRACE_EXPORTER=jaeger path (reference gofr.go:305-311 uses OTLP-gRPC;
    OTLP/HTTP carries the same payload without a generated-proto dependency)."""

    def __init__(self, endpoint: str, service_name: str):
        self.endpoint = endpoint  # e.g. http://host:4318/v1/traces
        self.service_name = service_name

    def export(self, spans: list[Span]) -> None:
        payload = {
            "resourceSpans": [
                {
                    "resource": {
                        "attributes": [
                            {
                                "key": "service.name",
                                "value": {"stringValue": self.service_name},
                            }
                        ]
                    },
                    "scopeSpans": [
                        {
                            "scope": {"name": "gofr-tpu"},
                            "spans": [
                                {
                                    "traceId": s.trace_id,
                                    "spanId": s.span_id,
                                    **(
                                        {"parentSpanId": s.parent_id}
                                        if s.parent_id
                                        else {}
                                    ),
                                    "name": s.name,
                                    "kind": 2,  # SPAN_KIND_SERVER
                                    "startTimeUnixNano": str(s.start_ns),
                                    "endTimeUnixNano": str(s.end_ns or s.start_ns),
                                    "attributes": [
                                        {
                                            "key": str(k),
                                            "value": {"stringValue": str(v)},
                                        }
                                        for k, v in s.attributes.items()
                                    ],
                                    "status": {
                                        "code": 2 if s.status == "ERROR" else 1
                                    },
                                    **(
                                        {
                                            "links": [
                                                {
                                                    "traceId": t,
                                                    "spanId": sp,
                                                }
                                                for t, sp in s.links
                                            ]
                                        }
                                        if s.links
                                        else {}
                                    ),
                                }
                                for s in spans
                            ],
                        }
                    ],
                }
            ]
        }
        req = urllib.request.Request(
            self.endpoint,
            data=json.dumps(payload).encode("utf-8"),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=5):  # noqa: S310
            pass


def span_to_dict(s: Span) -> dict:
    """Wire/debug form of a finished span — what the journey ring stores
    and `GET /.well-known/debug/traces` serves."""
    d = {
        "trace_id": s.trace_id,
        "span_id": s.span_id,
        "parent_id": s.parent_id,
        "name": s.name,
        "start_ns": s.start_ns,
        "end_ns": s.end_ns or s.start_ns,
        "duration_us": s.duration_us,
        "status": s.status,
        "attributes": {str(k): v for k, v in s.attributes.items()},
    }
    if s.links:
        d["links"] = [{"trace_id": t, "span_id": sp} for t, sp in s.links]
    return d


class RingExporter:
    """Bounded per-process span store: the last `capacity` finished spans,
    queryable by trace id, served at GET /.well-known/debug/traces.

    Unlike the push exporters this is not fed through the BatchProcessor
    thread — Tracer._on_end appends synchronously (one deque append under
    a small lock), so it tees alongside ANY configured exporter, including
    none, and a journey is queryable the instant its spans end. The fleet
    aggregator (gofr_tpu/router/) fans the same query over every backend
    and stitches the fragments: p99 spike -> exemplar trace id -> full
    cross-process timeline with zero external infra."""

    def __init__(self, capacity: int = 2048, service_name: str = ""):
        from collections import deque

        self.capacity = int(capacity)
        self.service_name = service_name
        self._lock = threading.Lock()
        self._spans: "deque[dict]" = deque(maxlen=max(1, self.capacity))

    def on_end(self, span: Span) -> None:
        d = span_to_dict(span)
        if self.service_name:
            d["service"] = self.service_name
        with self._lock:
            self._spans.append(d)

    def __len__(self) -> int:
        with self._lock:
            return len(self._spans)

    def query(self, trace_id: str) -> list[dict]:
        tid = (trace_id or "").strip().lower()
        with self._lock:
            return [s for s in self._spans if s["trace_id"] == tid]

    def trace_ids(self, limit: int = 64) -> list[dict]:
        """Most-recent-first summary of distinct trace ids in the ring."""
        with self._lock:
            spans = list(self._spans)
        seen: dict[str, dict] = {}
        for s in spans:  # oldest -> newest; newest wins the root name
            e = seen.setdefault(
                s["trace_id"],
                {"trace_id": s["trace_id"], "spans": 0, "root": s["name"]},
            )
            e["spans"] += 1
            if not s.get("parent_id"):
                e["root"] = s["name"]
        out = list(seen.values())[::-1]
        return out[: max(0, int(limit))]

    def dump(self, limit: int = 512) -> list[dict]:
        """Last `limit` finished spans, oldest -> newest: the trace
        section of a black-box incident bundle (gofr_tpu.flightrec) —
        the raw material a post-mortem re-stitches journeys from after
        the process that held the ring is gone."""
        with self._lock:
            spans = list(self._spans)
        return spans[-max(0, int(limit)):]

    def clear(self) -> int:
        """Flush the ring (shutdown path — the dead-engine-gauge rule:
        no stale journey fragments survive the process's serving life)."""
        with self._lock:
            n = len(self._spans)
            self._spans.clear()
        return n

    def stats(self) -> dict:
        with self._lock:
            return {"spans": len(self._spans), "capacity": self.capacity}


def stitch_spans(spans: list[dict]) -> dict:
    """Stitch span fragments (possibly from many processes) into one
    parent-linked journey tree. Children sort by start time; spans whose
    parent is absent from the set become roots (the fragment boundary).
    A well-threaded journey — router hop -> llm.request -> phases, with
    continuations parented under the original request span — yields
    exactly ONE root."""
    by_id: dict[str, dict] = {}
    nodes: list[dict] = []
    for s in sorted(spans, key=lambda s: s.get("start_ns", 0)):
        node = dict(s)
        node["children"] = []
        # keep first occurrence on span-id collision (dup fan-in replies)
        if node.get("span_id") in by_id:
            continue
        by_id[node["span_id"]] = node
        nodes.append(node)
    roots: list[dict] = []
    for node in nodes:
        parent = by_id.get(node.get("parent_id") or "")
        if parent is not None and parent is not node:
            parent["children"].append(node)
        else:
            roots.append(node)
    trace_ids = sorted({n["trace_id"] for n in nodes})
    processes = sorted(
        {str(n.get("process") or n.get("service") or "") for n in nodes} - {""}
    )
    return {
        "trace_id": trace_ids[0] if len(trace_ids) == 1 else trace_ids,
        "span_count": len(nodes),
        "processes": processes,
        "roots": roots,
    }


class BatchProcessor:
    """Queues ended spans; a daemon thread flushes batches to the exporter.
    Parity: reference batch span processor (gofr.go:318)."""

    def __init__(self, exporter: Exporter, max_batch: int = 512, interval_s: float = 2.0):
        self._exporter = exporter
        self._queue: queue.Queue[Span] = queue.Queue(maxsize=8192)
        self._max_batch = max_batch
        self._interval = interval_s
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True, name="gofr-trace-export")
        self._thread.start()

    def on_end(self, span: Span) -> None:
        try:
            self._queue.put_nowait(span)
        except queue.Full:
            pass  # drop rather than block the hot path

    def _drain(self) -> list[Span]:
        batch: list[Span] = []
        while len(batch) < self._max_batch:
            try:
                batch.append(self._queue.get_nowait())
            except queue.Empty:
                break
        return batch

    def _run(self) -> None:
        while not self._stop.wait(self._interval):
            self._flush()
        self._flush()

    def _flush(self) -> None:
        batch = self._drain()
        if batch:
            try:
                self._exporter.export(batch)
            except Exception:  # noqa: BLE001 - exporter failures must not kill serving
                pass

    def shutdown(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)
        self._flush()
        self._exporter.shutdown()


class Tracer:
    """Factory for spans; owns the processor. One per app."""

    def __init__(self, service_name: str = "gofr-tpu-app", processor: BatchProcessor | None = None, ring: RingExporter | None = None):
        self.service_name = service_name
        self._processor = processor
        self.ring = ring

    def start_span(self, name: str, *, traceparent: str | None = None, attributes: dict | None = None) -> Span:
        parent = _current_span.get()
        if parent is not None and parent.end_ns == 0:
            trace_id, parent_id = parent.trace_id, parent.span_id
        else:
            ctx = parse_traceparent(traceparent)
            if ctx:
                trace_id, parent_id = ctx
            else:
                trace_id, parent_id = _rand_hex(16), None
        span = Span(name, trace_id, _rand_hex(8), parent_id, self)
        if attributes:
            span.attributes.update(attributes)
        span._token = _current_span.set(span)
        return span

    def start_detached_span(
        self, name: str, *, parent: tuple[str, str] | None = None,
        attributes: dict | None = None,
    ) -> Span:
        """A span that does NOT become the active contextvar span. For work
        that outlives the submitting call and ends on another thread (the
        LLM engine's scheduler/collector): the caller captures its trace
        context once — (trace_id, span_id) — and every later phase span is
        parented explicitly instead of through the contextvar, which does
        not flow across plain threads. end() still ships to the exporter."""
        trace_id, parent_id = parent if parent else (_rand_hex(16), None)
        span = Span(name, trace_id, _rand_hex(8), parent_id, self)
        if attributes:
            span.attributes.update(attributes)
        return span

    def record_span(
        self, name: str, *, trace_id: str, parent_id: str | None,
        start_ns: int, end_ns: int, attributes: dict | None = None,
        status: str = "OK", links: list[tuple[str, str]] | None = None,
    ) -> Span:
        """Record an already-elapsed interval as a finished span — the
        retrospective form the engine uses for phases it only measures
        after the fact (a decode chunk's dispatch->fetch window is known
        when the fetch completes, on a different thread from dispatch)."""
        span = Span(name, trace_id, _rand_hex(8), parent_id, None)
        span.start_ns = start_ns
        span.end_ns = max(end_ns, start_ns)
        if attributes:
            span.attributes.update(attributes)
        if links:
            span.links = list(links)
        span.status = status
        self._on_end(span)
        return span

    def _on_end(self, span: Span) -> None:
        if self.ring is not None:
            self.ring.on_end(span)
        if self._processor is not None:
            self._processor.on_end(span)

    def shutdown(self) -> None:
        if self._processor is not None:
            self._processor.shutdown()
        if self.ring is not None:
            self.ring.clear()


def current_span() -> Span | None:
    return _current_span.get()


def new_tracer(config, logger=None) -> Tracer:
    """Build tracer from config. TRACE_EXPORTER switch matches the
    reference's jaeger|zipkin|gofr (gofr.go:305-316) plus console|memory
    dev exporters: jaeger/otlp -> OTLP/HTTP JSON, zipkin -> Zipkin-v2 JSON,
    gofr -> the reference's hosted zipkin-shaped endpoint (exporter.go:36)."""
    name = (config.get("APP_NAME") or "gofr-tpu-app") if config else "gofr-tpu-app"
    exporter_kind = (config.get("TRACE_EXPORTER") or "").lower() if config else ""
    exporter: Exporter | None = None
    if exporter_kind == "zipkin":
        host = config.get_or_default("TRACER_HOST", "localhost")
        port = config.get_or_default("TRACER_PORT", "9411")
        url = config.get_or_default("TRACER_URL", f"http://{host}:{port}/api/v2/spans")
        exporter = ZipkinExporter(url, name)
    elif exporter_kind in ("jaeger", "otlp"):
        host = config.get_or_default("TRACER_HOST", "localhost")
        port = config.get_or_default("TRACER_PORT", "4318")
        url = config.get_or_default(
            "TRACER_URL", f"http://{host}:{port}/v1/traces"
        )
        exporter = OTLPHTTPExporter(url, name)
    elif exporter_kind == "gofr":
        url = config.get_or_default(
            "TRACER_URL", "https://tracer-api.gofr.dev/api/spans"
        )
        exporter = ZipkinExporter(url, name)
    elif exporter_kind == "console":
        exporter = ConsoleExporter(logger)
    elif exporter_kind == "memory":
        exporter = InMemoryExporter()
    # Journey ring: on by default (it IS the zero-infra trace store the
    # debug/traces endpoint and the fleet stitcher read); TRACE_RING_SPANS=0
    # opts out, any other value sizes the ring.
    try:
        ring_cap = int(
            config.get_or_default("TRACE_RING_SPANS", "2048") if config else 2048
        )
    except (TypeError, ValueError):
        ring_cap = 2048
    ring = RingExporter(ring_cap, name) if ring_cap > 0 else None
    if exporter is None:
        return Tracer(name, None, ring)
    proc = BatchProcessor(exporter)
    t = Tracer(name, proc, ring)
    t.exporter = exporter  # type: ignore[attr-defined] - exposed for tests
    return t
