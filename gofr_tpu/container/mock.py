"""One-call mock container for tests.

Parity: reference pkg/gofr/container/mock_container.go:19-32 —
`NewMockContainer(t)` returns a container plus every datasource mock,
pre-wired. The reference hands back gomock stubs; this framework's
philosophy (MiniRedis, FakeKafka, in-memory sqlite) is stronger: the
"mocks" are real protocol/datasource implementations running in-process,
so tests exercise the same code paths production does.

    from gofr_tpu import new_mock_container

    c, mocks = new_mock_container()
    c.sql.exec("CREATE TABLE t (id INTEGER)")
    mocks.tpu.results["mnist"] = [0.9]
    ...
    mocks.close()          # or: with-less tests rely on GC/daemon threads

`mocks` carries the backing fakes for assertions (mocks.redis_server,
mocks.kafka_broker when enabled) and mocks.close() tears everything down.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

__all__ = ["Mocks", "new_mock_container"]


@dataclass
class Mocks:
    config: Any
    metrics: Any
    tpu: Any
    sql: Any = None
    redis: Any = None
    redis_server: Any = None
    pubsub: Any = None
    kafka_broker: Any = None
    mongo: Any = None
    _container: Any = field(default=None, repr=False)

    def close(self) -> None:
        if self._container is not None:
            self._container.close()
        if self.redis_server is not None:
            self.redis_server.stop()
        if self.kafka_broker is not None:
            self.kafka_broker.close()

    def __enter__(self) -> "Mocks":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def new_mock_container(
    *,
    sql: bool = True,
    redis: bool = True,
    pubsub: str = "memory",  # "memory" | "kafka" | "none"
    mongo: bool = True,
    overrides: dict[str, str] | None = None,
):
    """Build a Container with every datasource backed by an in-process
    stand-in. Returns (container, mocks).

    - sql: real in-memory sqlite through the framework's DB wrapper
    - redis: MiniRedis server + the framework's RESP client connected to it
    - pubsub: MemoryPubSub, or a FakeKafkaBroker + the real Kafka client
    - mongo: the in-memory document store behind the provider seam
    - tpu: MockTPU (record calls, canned results — no jax)
    """
    from ..config import new_mock_config
    from ..datasource.tpu import MockTPU
    from ..logging import new_logger
    from ..metrics import new_metrics_manager
    from . import Container

    cfg = new_mock_config({"APP_NAME": "mock-app", **(overrides or {})})
    c = Container(config=cfg, logger=new_logger(level_name="ERROR"))
    c.metrics_manager = new_metrics_manager(c.logger)
    c.register_framework_metrics()

    mocks = Mocks(config=cfg, metrics=c.metrics_manager, tpu=MockTPU(), _container=c)
    c.tpu_runtime = mocks.tpu

    if sql:
        from ..datasource.sql import new_sql_mocks

        c.sql = mocks.sql = new_sql_mocks(c.logger, c.metrics_manager)

    if redis:
        from ..datasource.redis import Redis
        from ..testutil import MiniRedis

        mocks.redis_server = MiniRedis().start()
        c.redis = mocks.redis = Redis(
            "127.0.0.1", mocks.redis_server.port,
            logger=c.logger, metrics=c.metrics_manager,
        )

    if pubsub == "memory":
        from ..datasource.pubsub import MemoryPubSub

        c.pubsub = mocks.pubsub = MemoryPubSub(c.logger, c.metrics_manager)
    elif pubsub == "kafka":
        from ..datasource.pubsub.kafka import KafkaConfig, KafkaPubSub
        from ..testutil.fakekafka import FakeKafkaBroker

        mocks.kafka_broker = FakeKafkaBroker()
        kcfg = KafkaConfig(new_mock_config({
            "PUBSUB_BROKER": mocks.kafka_broker.address,
            "KAFKA_BATCH_TIMEOUT": "20",
        }))
        c.pubsub = mocks.pubsub = KafkaPubSub(kcfg, logger=c.logger, metrics=c.metrics_manager)
    elif pubsub != "none":
        raise ValueError(f"unknown mock pubsub backend {pubsub!r}")

    if mongo:
        from ..datasource.mongo import InMemoryMongo

        c.add_mongo(InMemoryMongo())
        mocks.mongo = c.mongo

    return c, mocks
