"""Container: the dependency-injection hub handed to every handler.

Parity: reference pkg/gofr/container/ — Container struct (container.go:28-41),
Create wiring from config (container.go:73-154), framework metrics
registration (container.go:166-198), health aggregation (health.go:8-28),
datasource interface seams (datasources.go:13-33).

TPU-first addition: the container owns the TPURuntime (model registry +
device mesh + dynamic batchers) exactly as it owns Redis/SQL in the
reference — `ctx.tpu()` is a datasource.
"""

from __future__ import annotations

import time
from typing import Any

from .. import logging as gl
from ..config import Config
from ..logging.remote import RemoteLevelLogger
from ..metrics import (
    DATASOURCE_BUCKETS,
    HTTP_BUCKETS,
    TPU_BUCKETS,
    Manager,
    new_metrics_manager,
)
from ..version import FRAMEWORK


class Container:
    """Holds logger, config, metrics, datasources, outbound services, TPU."""

    def __init__(self, config: Config | None = None, logger: gl.Logger | None = None):
        self.config = config
        self.logger: gl.Logger = logger or gl.new_logger()
        self.app_name = "gofr-tpu-app"
        self.app_version = "dev"
        self.services: dict[str, Any] = {}  # outbound HTTP services
        self.metrics_manager: Manager | None = None
        self.redis = None
        self.sql = None
        self.pubsub = None
        self.mongo = None
        self.tpu_runtime = None
        # scale-out proxy core (gofr_tpu.router.new_router_app attaches)
        self.front_router = None
        self.start_time = time.time()

    # -- construction (container.go:73-154) --
    @classmethod
    def create(cls, config: Config) -> "Container":
        c = cls(config=config)
        c.app_name = config.get_or_default("APP_NAME", "gofr-tpu-app")
        c.app_version = config.get_or_default("APP_VERSION", "dev")

        # TPU_PLATFORM=cpu|tpu pins the jax backend. Applied here — before
        # any user code can touch jax — because backend choice is global and
        # first-touch-wins (the runtime re-checks, but by then user model
        # init may already have initialized the wrong platform).
        platform = config.get("TPU_PLATFORM")
        if platform:
            from ..utils import pin_jax_platform

            pin_jax_platform(platform, c.logger)

        c.logger = RemoteLevelLogger(
            gl.level_from_string(config.get("LOG_LEVEL")),
            config.get("REMOTE_LOG_URL") or None,
            config.get_float("REMOTE_LOG_FETCH_INTERVAL", 15.0),
        )
        c.logger.debug("Container is being created")

        c.metrics_manager = new_metrics_manager(c.logger)
        c.register_framework_metrics()
        c.metrics_manager.set_gauge(
            "app_info", 1.0, app_name=c.app_name, app_version=c.app_version, framework_version=FRAMEWORK
        )

        # Datasources are wired only when configured, as in the reference.
        if config.get("REDIS_HOST"):
            from ..datasource.redis import new_client as new_redis

            c.redis = new_redis(config, c.logger, c.metrics_manager)
        if config.get("DB_DIALECT") or config.get("DB_HOST"):
            from ..datasource.sql import new_sql

            c.sql = new_sql(config, c.logger, c.metrics_manager)
        backend = (config.get("PUBSUB_BACKEND") or "").upper()
        if backend:
            from ..datasource.pubsub import new_pubsub

            c.pubsub = new_pubsub(backend, config, c.logger, c.metrics_manager)

        # TPU runtime is lazy: devices are touched on first use or when the
        # app registers a model, so pure-web apps never initialize jax.
        return c

    def register_framework_metrics(self) -> None:
        """Parity: container.go:166-198 (renamed go->python runtime gauges)."""
        m = self.metrics_manager
        assert m is not None
        m.new_gauge("app_info", "static app info")
        m.new_gauge("app_python_threads", "live thread count")
        m.new_gauge("app_sys_memory_rss", "resident set size bytes")
        m.new_gauge("app_python_gc_gen0", "gen0 allocations since last gc")
        m.new_gauge("app_python_num_gc", "completed gc collections")
        m.new_histogram("app_http_response", "http server response time s", HTTP_BUCKETS)
        m.new_histogram("app_http_service_response", "outbound http call time s", HTTP_BUCKETS)
        m.new_histogram("app_redis_stats", "redis op time s", DATASOURCE_BUCKETS)
        m.new_histogram("app_sql_stats", "sql op time s", DATASOURCE_BUCKETS)
        m.new_histogram("app_mongo_stats", "mongo op time s", DATASOURCE_BUCKETS)
        m.new_gauge("app_sql_open_connections", "open sql connections")
        m.new_gauge("app_sql_inuse_connections", "in-use sql connections")
        # TPU datasource metrics (the build's app_tpu_stats analogue of app_sql_stats)
        m.new_histogram("app_tpu_stats", "tpu execute time s", TPU_BUCKETS)
        m.new_histogram("app_tpu_batch_size", "dynamic batch sizes", (1, 2, 4, 8, 16, 32, 64, 128, 256))
        m.new_histogram("app_tpu_queue_wait", "batch queue wait s", TPU_BUCKETS)
        # Pub/sub counters (container.go:194-197)
        m.new_counter("app_pubsub_publish_total_count", "messages published")
        m.new_counter("app_pubsub_publish_success_count", "messages published ok")
        m.new_counter("app_pubsub_subscribe_total_count", "subscribe receives")
        m.new_counter("app_pubsub_subscribe_success_count", "messages handled ok")

    # -- TPU runtime accessor --
    def tpu(self):
        if self.tpu_runtime is None:
            from ..datasource.tpu import TPURuntime

            self.tpu_runtime = TPURuntime(
                self.config, self.logger, self.metrics_manager,
                # the App sets container.tracer after create(); engines
                # registered before that (rare) simply serve untraced
                tracer=getattr(self, "tracer", None),
            )
        return self.tpu_runtime

    # -- health aggregation (health.go:8-28) --
    def health(self) -> dict[str, Any]:
        out: dict[str, Any] = {}
        if self.sql is not None:
            out["sql"] = self.sql.health_check()
        if self.redis is not None:
            out["redis"] = self.redis.health_check()
        if self.pubsub is not None:
            out["pubsub"] = self.pubsub.health()
        if self.mongo is not None:
            out["mongo"] = self.mongo.health_check()
        if self.tpu_runtime is not None:
            out["tpu"] = self.tpu_runtime.health_check()
        for name, svc in self.services.items():
            try:
                out[name] = svc.health_check_sync()
            except Exception as e:  # noqa: BLE001
                out[name] = {"status": "DOWN", "details": {"error": str(e)}}
        out["app"] = {
            "status": "UP",
            "details": {
                "name": self.app_name,
                "version": self.app_version,
                "framework": FRAMEWORK,
                "uptime_s": round(time.time() - self.start_time, 3),
            },
        }
        return out

    def get_http_service(self, name: str):
        return self.services.get(name)

    def get_publisher(self):
        return self.pubsub

    def get_subscriber(self):
        return self.pubsub

    # -- metrics facade for user code (examples/using-custom-metrics) --
    @property
    def metrics(self) -> Manager:
        assert self.metrics_manager is not None, "metrics not initialized"
        return self.metrics_manager

    def add_mongo(self, provider) -> None:
        """Wire a user-constructed Mongo provider (externalDB.go:5-12):
        inject logger/metrics, connect, expose as ctx.mongo."""
        from ..datasource.mongo import InstrumentedMongo

        db = InstrumentedMongo(provider, self.logger, self.metrics_manager)
        provider.connect()
        self.mongo = db

    def close(self) -> None:
        # front_router: the scale-out proxy core (poll thread, breaker
        # probes, autoscaler-managed engine processes) — attached by
        # gofr_tpu.router.new_router_app
        for attr in ("redis", "sql", "pubsub", "mongo", "tpu_runtime",
                     "front_router"):
            ds = getattr(self, attr)
            if ds is not None and hasattr(ds, "close"):
                try:
                    ds.close()
                except Exception:  # noqa: BLE001
                    pass
        if isinstance(self.logger, RemoteLevelLogger):
            self.logger.close()


def new_container(config: Config) -> Container:
    return Container.create(config)
