"""Speculative decoding: host-side n-gram drafting + acceptance policy.

Decode is memory-bandwidth-bound — every output token streams the whole
weight set (plus the live KV prefix) for ONE token of useful work.
Speculative decoding (Leviathan et al., "Fast Inference from Transformers
via Speculative Decoding", 2023) converts the spare FLOPs into multiple
tokens per forward pass: a cheap DRAFTER proposes k tokens, the target
model scores all k+1 positions in one pass (MXU-parallel, roughly the
cost of one decode step at these widths), and an acceptance rule keeps
the longest prefix the target model agrees with — output distribution
exactly preserved.

This module owns the HOST side of the machinery for gofr_tpu.llm:

- **NGramDrafter** — prompt-lookup drafting (Saxena, "Prompt Lookup
  Decoding", 2023): match the request's trailing n-gram against its own
  prompt + emitted history and propose the continuation of the most
  recent earlier occurrence. Zero extra device memory and no draft model
  — the right first drafter for an engine whose KV budget is already
  spoken for — and extremely effective on the repetitive/structured
  output (code, JSON, extraction, summarized quotes) where decode
  throughput hurts most.

- **accept_length** — the acceptance rule, host-mirrored for tests (the
  serving engine evaluates the same rule ON DEVICE inside the fused
  verify program so the chain tail/cursors stay device-resident): accept
  the longest prefix where draft[i] == sampled[i]. With the verifier
  sampling position i from the target distribution p_i via the engine's
  own top-k `_sample` machinery, this IS Leviathan rejection sampling
  for a deterministic (delta-distribution) drafter: draft token x is
  accepted with probability p_i(x), and on rejection the emitted token
  is distributed as p_i conditioned on != x — the residual distribution
  — so the output matches plain sampling exactly. At temperature 0 both
  sides reduce to argmax and spec-on is token-identical to spec-off.

- **draft_len** — per-request adaptive draft length from an acceptance
  EMA: adversarial text (no self-similarity, ~0% acceptance) backs the
  draft off to 0 (plain decode — one token per pass, the spec-off cost)
  so speculation can never regress below baseline, with a periodic
  1-token probe so a request whose tail TURNS repetitive recovers.

Knobs: ``TPU_LLM_SPEC`` (off by default), ``TPU_LLM_SPEC_DRAFT``
(max draft length, default 4) — docs/advanced-guide/speculative-decoding.md.
"""

from __future__ import annotations

import numpy as np

__all__ = [
    "NGramDrafter",
    "accept_length",
    "draft_len",
    "SPEC_DRAFT_DEFAULT",
    "SPEC_EMA_ALPHA",
    "SPEC_BACKOFF_EMA",
    "SPEC_PROBE_EVERY",
]

SPEC_DRAFT_DEFAULT = 4  # TPU_LLM_SPEC_DRAFT default (verify width 5)
SPEC_EMA_ALPHA = 0.3  # acceptance-EMA step per verify with proposals
SPEC_BACKOFF_EMA = 0.2  # EMA below this -> plain decode (draft 0)
SPEC_PROBE_EVERY = 16  # backed-off requests probe 1 draft token this often


class NGramDrafter:
    """Prompt-lookup drafter: propose the continuation of the most recent
    earlier occurrence of the sequence's trailing n-gram.

    Longest pattern first (``max_ngram`` down to ``min_ngram``) — a
    longer matched context predicts the continuation better — and within
    a pattern length the MOST RECENT earlier occurrence wins (locality:
    recent text predicts the immediate future better than the distant
    prompt). Pure host-side string matching over the tokens the engine
    already tracks for failover re-seeding, so drafting costs no device
    memory and no extra model.

    The scan runs on the token stream's int32 byte image via
    ``bytes.rfind`` (C speed; a Python token-list scan at 4k-token
    histories costs milliseconds per slot per step, which at 32 slots
    would burn the scheduler thread). Byte matches are validated to
    4-byte token alignment — an unaligned hit (token boundaries
    straddled) re-searches below it.
    """

    def __init__(self, max_ngram: int = 3, min_ngram: int = 1):
        if min_ngram < 1 or max_ngram < min_ngram:
            raise ValueError(
                f"need 1 <= min_ngram <= max_ngram, got {min_ngram}, {max_ngram}"
            )
        self.max_ngram = max_ngram
        self.min_ngram = min_ngram

    def draft(self, tokens: list[int], k: int) -> list[int]:
        """Up to ``k`` proposed continuation tokens for ``tokens`` (the
        request's prompt + emitted history, newest last). Empty when no
        earlier occurrence of any trailing n-gram exists — the engine
        then runs a plain decode step for the slot."""
        t = len(tokens)
        if k <= 0 or t < self.min_ngram + 1:
            return []
        buf = np.asarray(tokens, np.int32).tobytes()
        for n in range(min(self.max_ngram, t - 1), self.min_ngram - 1, -1):
            pat = buf[(t - n) * 4:]
            # Two search ceilings: prefer the most recent occurrence
            # whose continuation has a FULL k tokens before the sequence
            # end (on periodic text pure recency always matches right at
            # the end and truncates the draft to the period), falling
            # back to the most recent occurrence with ANY continuation.
            # A ceiling bounds the END of the match (rfind semantics);
            # match start must be an earlier occurrence, token index
            # <= t - n - 1.
            for last_start in (t - n - k, t - n - 1):
                if last_start < 0:
                    continue
                pos = buf.rfind(pat, 0, last_start * 4 + len(pat))
                while pos != -1 and pos % 4:
                    # unaligned byte hit (token boundaries straddled):
                    # the next candidate must END before this false one
                    pos = buf.rfind(pat, 0, pos + len(pat) - 1)
                if pos == -1:
                    continue
                cont = tokens[pos // 4 + n :][:k]
                if cont:
                    return list(cont)
        return []


def accept_length(draft: list[int], sampled: list[int]) -> int:
    """Longest-agreeing-prefix acceptance: the number of draft tokens
    accepted, ``a = max { j : draft[i] == sampled[i] for all i < j }``.
    The emitted span is then ``sampled[: a + 1]`` — the ``a`` accepted
    draft tokens (each equal to the target model's own sample at its
    position) plus the bonus token sampled at the first disagreeing (or
    final) position, exactly as in Leviathan et al. Host mirror of the
    device-side rule (tests drive both against each other)."""
    a = 0
    for d, s in zip(draft, sampled):
        if d != s:
            break
        a += 1
    return a


def draft_len(ema: float, kmax: int, plain_streak: int) -> int:
    """Adaptive draft length for one request: scale the draft to the
    acceptance EMA, floor at 1 while speculation pays at all, and back
    off to 0 (plain decode — the spec-off baseline cost) once the EMA
    drops below ``SPEC_BACKOFF_EMA``. A backed-off request re-probes
    with a single draft token every ``SPEC_PROBE_EVERY`` plain passes —
    without the probe, one adversarial stretch would disable speculation
    for the request's whole remaining stream even if its tail turns
    repetitive."""
    if kmax <= 0:
        return 0
    if ema < SPEC_BACKOFF_EMA:
        return 1 if plain_streak >= SPEC_PROBE_EVERY else 0
    return max(1, min(kmax, int(round(ema * kmax))))
